"""Paged KV cache tests: the host-side block allocator, the engine's
paged cache APIs, and the load-bearing property of the whole design —
greedy decode through block tables is token-for-token identical to the
dense resident cache, on both acceptance meshes.

Parity is exact array equality (CPU greedy decode is deterministic, and
with ``kv_dtype=None``/``"bfloat16"`` the pool stores the same bits the
dense cache would).  ``kv_dtype="int8"`` is lossy by construction, so it
gets a logits-tolerance check at the model layer plus an end-to-end
completion check, not bitwise parity.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models.gpt2 import GPT2, GPT2Config, PagedKVConfig
from distributed_tensorflow_tpu.serve import ContinuousScheduler, ServeEngine
from distributed_tensorflow_tpu.serve.paged import (
    TRASH_BLOCK,
    BlockAllocator,
    BlockExhaustedError,
)


def _mixed_requests(vocab, n=20, seed=1):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        length = (4, 6, 9)[i % 3]
        horizon = (2, 5, 3, 7)[i % 4]
        reqs.append((rng.integers(0, vocab, size=(length,), dtype=np.int32),
                     horizon))
    return reqs


def _fixed_reference(engine, prompt, max_new_tokens):
    rows = engine.bucket_rows(1)
    out = engine.generate(np.repeat(prompt[None, :], rows, axis=0),
                          max_new_tokens)
    return out[0]


@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


# ---------------------------------------------------------------------------
# BlockAllocator: pure host-side unit tests
# ---------------------------------------------------------------------------

class TestBlockAllocator:
    def test_fresh_pool_allocates_low_ids_first(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        assert a.capacity == 7          # block 0 reserved
        assert a.allocate(3) == [1, 2, 3]
        assert a.free_count == 4 and a.used_count == 3

    def test_trash_block_never_handed_out(self):
        a = BlockAllocator(num_blocks=4, block_size=2)
        assert TRASH_BLOCK not in a.allocate(3)

    def test_exhaustion_raises(self):
        a = BlockAllocator(num_blocks=4, block_size=2)
        a.allocate(2)
        with pytest.raises(BlockExhaustedError, match="only 1/3 free"):
            a.allocate(2)
        # the failed call must not have consumed anything
        assert a.free_count == 1

    def test_free_and_lifo_reuse(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        first = a.allocate(3, slot=5)
        a.free(first)
        assert a.free_count == a.capacity
        # LIFO: the just-freed blocks come back first, in reverse order
        assert a.allocate(3) == first[::-1]

    def test_double_free_and_trash_free_rejected(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        blocks = a.allocate(2)
        a.free(blocks)
        with pytest.raises(ValueError, match="double free"):
            a.free([blocks[0]])
        with pytest.raises(ValueError, match="trash"):
            a.free([TRASH_BLOCK])

    def test_stats_and_high_water(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        held = a.allocate(5)
        a.free(held[2:])
        s = a.stats()
        assert s["blocks_total"] == 7.0
        assert s["blocks_in_use"] == 2.0
        assert s["blocks_free"] == 5.0
        assert s["blocks_high_water"] == 5.0  # peak, not current
        assert s["block_utilization"] == pytest.approx(2 / 7)

    def test_blocks_for_tokens(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        assert [a.blocks_for_tokens(t) for t in (0, 1, 4, 5, 8)] == \
            [0, 1, 1, 2, 2]

    def test_geometry_validation(self):
        with pytest.raises(ValueError, match="num_blocks"):
            BlockAllocator(num_blocks=1, block_size=4)
        with pytest.raises(ValueError, match="block_size"):
            BlockAllocator(num_blocks=4, block_size=0)


class TestPagedKVConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            PagedKVConfig(block_size=0)
        with pytest.raises(ValueError):
            PagedKVConfig(num_blocks=1)
        with pytest.raises(TypeError):
            PagedKVConfig(kv_dtype="not_a_dtype")

    def test_geometry_helpers(self):
        cfg = PagedKVConfig(block_size=8, num_blocks=16)
        assert cfg.usable_blocks == 15
        assert cfg.blocks_for(17) == 3
        assert cfg.max_blocks_per_slot(32) == 4

    def test_storage_dtype(self):
        assert PagedKVConfig().storage_dtype(jnp.bfloat16) == jnp.bfloat16
        assert (PagedKVConfig(kv_dtype="int8").storage_dtype(jnp.bfloat16)
                == jnp.int8)
        assert (PagedKVConfig(kv_dtype="float32").storage_dtype(jnp.bfloat16)
                == jnp.float32)
        assert PagedKVConfig(kv_dtype="int8").quantized


# ---------------------------------------------------------------------------
# Engine layer: paged cache init + call validation
# ---------------------------------------------------------------------------

class TestEnginePagedAPIs:
    def test_init_paged_cache_validates_geometry(self, gpt2_engine):
        pcfg = PagedKVConfig(block_size=8, num_blocks=64)
        with pytest.raises(ValueError, match="multiple"):
            gpt2_engine.init_paged_cache(3, 16, paged=pcfg)
        n_pos = gpt2_engine.module.cfg.n_positions
        with pytest.raises(ValueError, match="n_positions"):
            gpt2_engine.init_paged_cache(8, n_pos + 1, paged=pcfg)
        # a pool that cannot hold even ONE max-length request is an error
        with pytest.raises(ValueError, match="usable blocks"):
            gpt2_engine.init_paged_cache(
                8, 32, paged=PagedKVConfig(block_size=8, num_blocks=4))

    def test_paged_and_block_tables_go_together(self, gpt2_engine):
        pcfg = PagedKVConfig(block_size=8, num_blocks=33)
        cache = gpt2_engine.init_paged_cache(8, 32, paged=pcfg)
        prompt = np.zeros((1, 4), np.int32)
        with pytest.raises(ValueError, match="together"):
            gpt2_engine.prefill_into_slots(cache, prompt, [0], paged=pcfg)
        with pytest.raises(ValueError, match="together"):
            gpt2_engine.decode_slots(
                cache, np.zeros((8, 1), np.int32), np.ones((8,), bool),
                block_tables=np.zeros((8, 4), np.int32))

    def test_sized_down_pool_shrinks_kv_hbm(self, gpt2_engine):
        """The memory claim at the byte level: a pool at ~half the dense
        token capacity costs <= 0.5x the dense cache bytes; int8 storage
        roughly halves it again (scales cost a little back)."""
        dense = gpt2_engine.cache_hbm_bytes(
            gpt2_engine.init_slot_cache(8, 32))
        half_pool = PagedKVConfig(block_size=8, num_blocks=17)  # 16 usable
        paged = gpt2_engine.cache_hbm_bytes(
            gpt2_engine.init_paged_cache(8, 32, paged=half_pool))
        int8 = gpt2_engine.cache_hbm_bytes(gpt2_engine.init_paged_cache(
            8, 32, paged=PagedKVConfig(block_size=8, num_blocks=17,
                                       kv_dtype="int8")))
        assert paged <= 0.60 * dense  # 0.5x K/V + index/trash overhead
        assert int8 < 0.70 * paged


# ---------------------------------------------------------------------------
# Parity: paged == dense, token for token
# ---------------------------------------------------------------------------

class TestPagedParity:
    def test_mixed_traffic_parity_mesh_dp(self, gpt2_engine):
        """THE acceptance property on the data=8 mesh: greedy decode
        through block tables matches the fixed-batch reference token for
        token, with more requests than slots so blocks are freed and
        reused mid-run."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=20)
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 cache_mode="paged", block_size=8) as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
            s = sched.stats()
            hist = sched.blocks_per_request_hist()
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))
        # every retired request returned its blocks
        assert s["blocks_in_use"] == 0.0
        assert s["blocks_high_water"] > 0.0
        assert sum(hist.values()) == len(reqs)
        assert s["blocks_per_request_max"] <= s["blocks_total"]

    def test_parity_under_tensor_parallel_mesh(self, mesh_2d):
        """Same parity on data=4 x tensor=2: pool heads shard over the
        tensor axis (gpt2_cache_rules), block tables stay host-side."""
        with ServeEngine("gpt2", mesh=mesh_2d, preset="tiny") as eng:
            vocab = eng.module.cfg.vocab_size
            reqs = _mixed_requests(vocab, n=10, seed=7)
            with ContinuousScheduler(eng, num_slots=4, max_total_len=32,
                                     cache_mode="paged",
                                     block_size=8) as sched:
                futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
                outs = [f.result(timeout=300) for f in futs]
            for (prompt, horizon), out in zip(reqs, outs):
                np.testing.assert_array_equal(
                    out, _fixed_reference(eng, prompt, horizon))

    def test_bfloat16_kv_dtype_is_exact(self, gpt2_engine):
        """kv_dtype naming the COMPUTE dtype is a plain cast-through —
        still bitwise, so still exact greedy parity."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=8, seed=3)
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 cache_mode="paged", block_size=8,
                                 kv_dtype="bfloat16") as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))


class TestInt8KV:
    def test_int8_logits_close_to_dense(self):
        """Model-layer tolerance: a prefill through the int8 pool must
        reproduce the plain forward's logits within quantization error
        (per-token scales, 127 levels)."""
        cfg = GPT2Config.tiny(dtype=jnp.float32)
        model = GPT2(cfg)
        tokens = np.asarray(jax.random.randint(
            jax.random.key(1), (2, 6), 0, cfg.vocab_size))
        params = model.init(jax.random.key(0), tokens)["params"]
        full = model.apply({"params": params}, jnp.asarray(tokens))

        pcfg = PagedKVConfig(block_size=4, num_blocks=9, kv_dtype="int8")
        bt = np.zeros((4, 2), np.int32)
        bt[3] = [1, 2]
        bt[0] = [3, 4]
        shapes = jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((4, 6), jnp.int32), decode=True,
            slot_ids=jnp.arange(4), paged=pcfg,
            block_tables=jnp.asarray(bt)))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        logits, _ = model.apply(
            {"params": params, "cache": cache}, jnp.asarray(tokens),
            decode=True, slot_ids=jnp.asarray([3, 0]), paged=pcfg,
            block_tables=jnp.asarray(bt), mutable=["cache"])
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                                   rtol=0.0, atol=0.05)

    @pytest.mark.serve_slow
    def test_int8_end_to_end_completes(self, gpt2_engine):
        """End-to-end int8 serving: all futures resolve with valid tokens
        of the right shape (bitwise parity is not promised here)."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=10, seed=5)
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 cache_mode="paged", block_size=8,
                                 kv_dtype="int8") as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
            s = sched.stats()
        assert s["completed"] == float(len(reqs))
        for (_, horizon), out in zip(reqs, outs):
            assert out.shape == (horizon,)
            assert (out >= 0).all() and (out < vocab).all()


# ---------------------------------------------------------------------------
# Backpressure + admission-time rejection
# ---------------------------------------------------------------------------

class TestBlockBackpressure:
    def test_exhausted_pool_defers_admission_not_correctness(self,
                                                             gpt2_engine):
        """A pool that fits only ONE request's worst case serializes
        admission (later requests wait for retirement's bulk-free) but
        every stream still matches the reference — backpressure, not
        corruption."""
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(11)
        reqs = [(rng.integers(0, vocab, size=(6,), dtype=np.int32), 6)
                for _ in range(3)]
        # worst case per request: blocks_for(6 + 6 - 1) = 3 of size 4;
        # 5 usable blocks -> the second request cannot co-reside.
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=16,
                                 cache_mode="paged", block_size=4,
                                 num_blocks=6) as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
            s = sched.stats()
        assert s["blocks_high_water"] <= 5.0
        assert s["completed"] == 3.0
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))

    def test_pool_too_small_for_one_request_rejected_at_init(self,
                                                             gpt2_engine):
        """A pool that cannot hold even one max-length request is a
        config error at CONSTRUCTION — nothing could ever decode, so it
        must not wait for a submit to fail."""
        with pytest.raises(ValueError, match="usable blocks"):
            ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                cache_mode="paged", block_size=4,
                                num_blocks=4, start=False)

    def test_submit_rejects_empty_prompt(self, gpt2_engine):
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=16,
                                 start=False) as sched:
            with pytest.raises(ValueError, match="at least one token"):
                sched.submit(np.zeros((0,), np.int32), max_new_tokens=4)

    def test_submit_rejects_overlong_request_in_both_modes(self,
                                                           gpt2_engine):
        for kw in ({}, {"cache_mode": "paged", "block_size": 4}):
            with ContinuousScheduler(gpt2_engine, num_slots=8,
                                     max_total_len=16, start=False,
                                     **kw) as sched:
                with pytest.raises(ValueError, match="max_total_len"):
                    sched.submit(np.zeros((12,), np.int32),
                                 max_new_tokens=8)

    def test_scheduler_config_validation(self, gpt2_engine):
        with pytest.raises(ValueError, match="cache_mode"):
            ContinuousScheduler(gpt2_engine, cache_mode="virtual",
                                start=False)
        with pytest.raises(ValueError, match="paged"):
            ContinuousScheduler(gpt2_engine, cache_mode="dense",
                                kv_dtype="int8", start=False)


# ---------------------------------------------------------------------------
# Block gauges on the stats / monitor surface
# ---------------------------------------------------------------------------

class TestBlockGauges:
    def test_dense_reports_trivially_full_pool(self, gpt2_engine):
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 block_size=8) as sched:
            out = sched.submit(np.arange(4, dtype=np.int32),
                               max_new_tokens=2).result(timeout=300)
            s = sched.stats()
            hist = sched.blocks_per_request_hist()
        assert len(out) == 2
        per_slot = 32 // 8
        assert s["blocks_total"] == float(8 * per_slot)
        assert s["blocks_in_use"] == s["blocks_total"]
        assert s["blocks_free"] == 0.0
        assert s["block_utilization"] == 1.0
        # dense: every request pins a full slot row for its lifetime
        assert hist == {per_slot: 1}
        assert s["kv_hbm_bytes"] > 0.0

    def test_monitor_logs_block_line(self, gpt2_engine, caplog):
        from distributed_tensorflow_tpu.obs import ServeMonitorHook

        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 cache_mode="paged", block_size=8) as sched:
            hook = ServeMonitorHook(sched, every_steps=1)
            sched.submit(np.arange(5, dtype=np.int32),
                         max_new_tokens=3).result(timeout=300)
            m = hook.metrics()
            with caplog.at_level(
                    logging.INFO,
                    logger="distributed_tensorflow_tpu.obs.serve"):
                hook.log(1)
        for key in ("serve_blocks_total", "serve_blocks_free",
                    "serve_block_utilization", "serve_blocks_high_water",
                    "serve_blocks_per_request_mean", "serve_kv_hbm_bytes"):
            assert key in m, m
        assert any("kv blocks=" in r.message and "util=" in r.message
                   for r in caplog.records)
