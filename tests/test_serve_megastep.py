"""Megastep-decode tests: fusing K decode iterations into one compiled
``lax.scan`` program must be a pure DISPATCH change — on-device sampling,
EOS masking and horizon countdown reproduce the host loop step for step,
so greedy output is bit-identical K on vs off — while the amortization it
buys is real: one launch and one fetch cover up to K tokens per slot.

Parity runs on BOTH acceptance meshes (pure data-parallel and
data=4 x tensor=2) and in dense AND paged cache modes, including a K
that does not divide the decode horizons (megastep carries chained
across program boundaries); composition tests pin the invariants
against chunked prefill, the prefix cache, and hot weight reload at a
megastep boundary.  EOS fired at an inner scan step j < K must trim on
host to the exact K=1 output — no post-EOS token leaks."""

import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.serve import ContinuousScheduler, ServeEngine


def _mixed_requests(vocab, seed=3):
    """Mixed traffic: horizons (2, 5, 3, 4) are all < 8 (whole requests
    finish inside one K=8 megastep) and straddle K=3 (5 = 3 + 2, the
    carry chains across two scans)."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i, length in enumerate((4, 6, 9, 8, 17, 5)):
        horizon = (2, 5, 3, 4)[i % 4]
        reqs.append((rng.integers(0, vocab, size=(length,), dtype=np.int32),
                     horizon))
    return reqs


def _fixed_reference(engine, prompt, max_new_tokens):
    rows = engine.bucket_rows(1)
    out = engine.generate(np.repeat(prompt[None, :], rows, axis=0),
                          max_new_tokens)
    return out[0]


def _run_all(sched, reqs):
    futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
    return [f.result(timeout=300) for f in futs]


@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


class TestCtorValidation:
    def test_zero_megastep_rejected(self, gpt2_engine):
        with pytest.raises(ValueError, match="megastep"):
            ContinuousScheduler(gpt2_engine, megastep=0, start=False)

    def test_stats_export_megastep(self, gpt2_engine):
        sched = ContinuousScheduler(gpt2_engine, num_slots=8,
                                    max_total_len=32, megastep=8,
                                    start=False)
        stats = sched.stats()
        assert stats["megastep"] == 8.0
        assert stats["megastep_launches"] == 0.0
        assert stats["megastep_tokens"] == 0.0
        assert stats["megastep_effective_steps"] == 0.0
        sched.close(timeout=0.1)


class TestMegastepParity:
    """Greedy output must be bit-identical K on vs off: the scan changes
    HOW MANY iterations one dispatch covers, never what any row decodes."""

    @pytest.mark.parametrize("cache_mode", ["dense", "paged"])
    def test_megastep_on_off_token_identical(self, gpt2_engine, cache_mode):
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab)
        kwargs = dict(num_slots=8, max_total_len=32)
        if cache_mode == "paged":
            kwargs.update(cache_mode="paged", block_size=4)
        with ContinuousScheduler(gpt2_engine, **kwargs) as sched:
            baseline = _run_all(sched, reqs)
        # K=8 swallows every horizon whole; K=3 forces ragged chains
        # (horizon 5 = one full scan + a 2-live-step tail).
        for steps in (8, 3):
            with ContinuousScheduler(gpt2_engine, megastep=steps,
                                     **kwargs) as sched:
                fused = _run_all(sched, reqs)
                stats = sched.stats()
                assert stats["megastep"] == float(steps)
                # The amortization claim: strictly fewer launches than
                # decoded tokens (K=1 pays one launch per token).
                assert 0 < stats["megastep_launches"] \
                    < stats["megastep_tokens"]
            for (prompt, horizon), base, out in zip(reqs, baseline, fused):
                np.testing.assert_array_equal(out, base)
                np.testing.assert_array_equal(
                    out, _fixed_reference(gpt2_engine, prompt, horizon))

    @pytest.mark.parametrize("cache_mode", ["dense", "paged"])
    def test_parity_on_2d_mesh(self, mesh_2d, cache_mode):
        """data=4 x tensor=2: the scan body's collectives and the paged
        scatter must compose with sharded params and the tensor-sharded
        resident cache."""
        with ServeEngine("gpt2", mesh=mesh_2d, preset="tiny") as eng:
            vocab = eng.module.cfg.vocab_size
            reqs = _mixed_requests(vocab, seed=5)
            kwargs = dict(num_slots=8, max_total_len=32)
            if cache_mode == "paged":
                kwargs.update(cache_mode="paged", block_size=4)
            with ContinuousScheduler(eng, **kwargs) as sched:
                baseline = _run_all(sched, reqs)
            with ContinuousScheduler(eng, megastep=8, **kwargs) as sched:
                fused = _run_all(sched, reqs)
            for base, out in zip(baseline, fused):
                np.testing.assert_array_equal(out, base)


class TestMegastepEos:
    def test_eos_mid_megastep_trims_to_k1_output(self, gpt2_engine):
        """A row whose EOS fires at inner scan step j < K stops advancing
        ON DEVICE (the alive mask freezes its token and cache index); the
        host trim walks ``done()`` exactly like the K=1 loop, so the
        result is token-identical and nothing past EOS leaks out."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = (np.arange(6, dtype=np.int32) * 5) % vocab
        horizon = 6
        ref = _fixed_reference(gpt2_engine, prompt, horizon)
        # Pick the first token whose value has not appeared before it:
        # greedy decode then stops exactly there, at an inner step < K.
        eos_idx = next(i for i in range(1, len(ref))
                       if ref[i] not in ref[:i])
        eos = int(ref[eos_idx])
        outs = {}
        for steps in (1, 8):
            with ContinuousScheduler(gpt2_engine, num_slots=8,
                                     max_total_len=32,
                                     megastep=steps) as sched:
                fut = sched.submit(prompt, max_new_tokens=horizon,
                                   eos_token=eos)
                outs[steps] = np.asarray(fut.result(timeout=300))
                if steps > 1:
                    # Every decode-appended token was counted (the first
                    # generated token comes from prefill); a post-EOS
                    # leak would show up as extra megastep_tokens.
                    stats = sched.stats()
                    assert stats["megastep_tokens"] == len(
                        outs[steps]) - 1
                    # Early exit: EOS at inner step j < K stops the
                    # while_loop once every row is dead — strictly fewer
                    # effective inner steps than launches * K, instead
                    # of riding out the masked no-op tail.
                    assert 0 < stats["megastep_effective_steps"] \
                        < stats["megastep_launches"] * steps
        np.testing.assert_array_equal(outs[8], outs[1])
        assert len(outs[8]) == eos_idx + 1 < horizon  # stopped mid-scan
        assert outs[8][-1] == eos
        assert eos not in outs[8][:-1]
        np.testing.assert_array_equal(outs[8], ref[:eos_idx + 1])


class TestMegastepReload:
    def test_reload_lands_at_megastep_boundary(self, gpt2_engine):
        """Weights staged mid-request swap in only at a megastep boundary:
        the in-flight request keeps its admission generation for every
        remaining scan (params ride the per-generation launch grouping),
        while the next admission picks up the new tag."""
        vocab = gpt2_engine.module.cfg.vocab_size
        whale = (np.arange(64, dtype=np.int32) * 3) % vocab
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=96,
                                 prefill_budget=2, megastep=4) as sched:
            gen0 = sched.generation
            fut = sched.submit(whale, max_new_tokens=6)  # 6 = 4 + 2 scans
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                s = sched.stats()
                if s["prefilling_slots"] >= 1.0 and s["prefill_chunks"] >= 1:
                    break
                time.sleep(0.001)
            else:
                pytest.fail("whale never observed mid-prefill")
            sched.update_params(gpt2_engine.params, generation=gen0 + 7)
            out = fut.result(timeout=300)
            assert fut.generation == gen0
            post = sched.submit(whale[:4], max_new_tokens=6)
            post.result(timeout=300)
            assert post.generation == gen0 + 7
            assert sched.generation == gen0 + 7
        np.testing.assert_array_equal(
            out, _fixed_reference(gpt2_engine, whale, 6))


class TestMegastepComposition:
    def test_chunked_prefill_composes(self, gpt2_engine):
        """Chunked prefill feeds admissions between megasteps; both are
        pure scheduling/dispatch changes, so stacking them stays
        bit-identical to the plain loop."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, seed=7)
        kwargs = dict(num_slots=8, max_total_len=32)
        with ContinuousScheduler(gpt2_engine, **kwargs) as sched:
            baseline = _run_all(sched, reqs)
        with ContinuousScheduler(gpt2_engine, prefill_budget=4, megastep=8,
                                 **kwargs) as sched:
            stacked = _run_all(sched, reqs)
            assert sched.stats()["prefill_chunks"] > len(reqs)
        for base, out in zip(baseline, stacked):
            np.testing.assert_array_equal(out, base)

    def test_prefix_cache_composes(self, gpt2_engine):
        """Prefix-mapped blocks skip prefill, then the megastep scatter
        appends behind them through the same block tables — hits and
        output must match the K=1 paged run."""
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(13)
        prefix = rng.integers(0, vocab, size=(8,), dtype=np.int32)
        reqs = [(np.concatenate([prefix, rng.integers(
                     0, vocab, size=(n,), dtype=np.int32)]), 3)
                for n in (4, 6, 9)]
        kwargs = dict(num_slots=8, max_total_len=32, cache_mode="paged",
                      block_size=4, prefix_cache=True)
        runs = []
        for steps in (1, 8):
            with ContinuousScheduler(gpt2_engine, megastep=steps,
                                     **kwargs) as sched:
                # Sequential submits: request N's prefix blocks are
                # registered before N+1 maps them, both runs identically.
                outs = [sched.submit(p, max_new_tokens=m).result(timeout=300)
                        for p, m in reqs]
                stats = sched.stats()
                runs.append((outs, stats["prefill_tokens_skipped"],
                             stats["prefix_hits"]))
        (base_outs, base_skip, base_hits), (outs, skip, hits) = runs
        assert skip == base_skip > 0
        assert hits == base_hits > 0
        for base, out in zip(base_outs, outs):
            np.testing.assert_array_equal(out, base)
