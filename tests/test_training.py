"""End-to-end training-slice tests: step, loop, checkpoint, train_lib.

Mirrors SURVEY.md §5's tier (a)/(b): unit + simulated-mesh tests.  The
acceptance bar for the slice is the reference's own: loss goes down on the
MNIST workload, checkpoints resume exactly, hooks observe what they should.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.checkpoint import CheckpointManager
from distributed_tensorflow_tpu.models import available_models, get_workload
from distributed_tensorflow_tpu.train_lib import TrainArgs, build_state_and_step, run
from distributed_tensorflow_tpu.training import (
    BF16,
    FP32,
    LoggingHook,
    NanHook,
    TrainLoop,
    TrainState,
    make_train_step,
)


def quadratic_loss(params, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, {"mae": jnp.mean(jnp.abs(pred - batch["y"]))}


def make_linear_state(lr=0.1):
    params = {"w": jnp.zeros((4, 1)), "b": jnp.zeros((1,))}
    return TrainState.create(
        apply_fn=lambda p, x: x @ p["w"] + p["b"],
        params=params,
        tx=optax.sgd(lr),
    )


def linear_batch(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w + 0.1
    return {"x": x, "y": y}


class TestTrainStep:
    def test_linear_regression_converges(self):
        state = make_linear_state()
        step = make_train_step(quadratic_loss, precision=FP32)
        batch = linear_batch()
        rng = jax.random.key(0)
        losses = []
        for _ in range(100):
            state, m = step(state, batch, rng)
            losses.append(float(m["loss"]))
        assert losses[-1] < 0.02 * losses[0]

    def test_grad_accum_matches_full_batch(self):
        # SGD: mean-of-microbatch-grads == full-batch grad, so one accum step
        # must equal one full-batch step exactly (up to fp assoc).
        batch = linear_batch(64)
        rng = jax.random.key(0)

        s_full = make_linear_state()
        step_full = make_train_step(quadratic_loss, precision=FP32)
        s_full, m_full = step_full(s_full, batch, rng)

        s_acc = make_linear_state()
        step_acc = make_train_step(
            quadratic_loss, grad_accum_steps=4, precision=FP32
        )
        s_acc, m_acc = step_acc(s_acc, batch, rng)

        np.testing.assert_allclose(
            np.asarray(s_full.params["w"]), np.asarray(s_acc.params["w"]),
            rtol=1e-5,
        )
        assert int(s_acc.step) == 1

    def test_clip_grad_norm(self):
        state = make_linear_state(lr=1.0)
        w_before = np.asarray(state.params["w"]).copy()  # state is donated
        step = make_train_step(
            quadratic_loss, precision=FP32, clip_grad_norm=1e-3
        )
        batch = linear_batch()
        new_state, m = step(state, batch, jax.random.key(0))
        delta = jnp.linalg.norm(np.asarray(new_state.params["w"]) - w_before)
        assert float(delta) <= 1.1e-3
        assert "grad_norm" in m

    def test_bf16_policy_keeps_master_f32(self):
        state = make_linear_state()
        step = make_train_step(quadratic_loss, precision=BF16)
        state, _ = step(state, linear_batch(), jax.random.key(0))
        assert state.params["w"].dtype == jnp.float32


class TestEval:
    def test_periodic_eval_in_training(self):
        from distributed_tensorflow_tpu.train_lib import TrainArgs, run

        result = run(TrainArgs(
            model="mnist", steps=20, batch_size=32, log_every=10,
            eval_every=10, eval_batches=2,
        ))
        assert result["final_step"] == 20
        assert "eval_loss" in result
        assert np.isfinite(result["eval_loss"])

    def test_checkpoint_knobs_flow_from_flags(self, tmp_path):
        """--max_to_keep / --sync_checkpoint reach the manager (VERDICT r4
        weak #7: train_lib hard-coded max_to_keep=3)."""
        from distributed_tensorflow_tpu.train_lib import (
            TrainArgs,
            parse_args,
            run,
        )

        args = parse_args([
            "--model=mnist", "--steps=10", "--batch_size=32",
            "--checkpoint_every=2", "--max_to_keep=1", "--sync_checkpoint",
            f"--checkpoint_dir={tmp_path / 'ckpt'}",
        ])
        assert args.max_to_keep == 1 and args.sync_checkpoint
        run(args)
        from distributed_tensorflow_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        try:
            assert mgr.all_steps() == [10]  # retained exactly max_to_keep
        finally:
            mgr.close()

    def test_table_dtype_flag_parses_and_validates(self):
        from distributed_tensorflow_tpu.train_lib import parse_args

        args = parse_args(["--model=wide_deep", "--table_dtype=bf16"])
        assert args.table_dtype == "bf16"
        import pytest

        from distributed_tensorflow_tpu.train_lib import TrainArgs, run

        with pytest.raises(ValueError, match="table_dtype"):
            run(TrainArgs(model="mnist", table_dtype="bf16", steps=1))

    def test_evaluator_role_consumes_checkpoints(self, tmp_path):
        from distributed_tensorflow_tpu.train_lib import (
            TrainArgs,
            run,
            run_evaluator,
        )

        ckpt = str(tmp_path / "ckpt")
        run(TrainArgs(
            model="mnist", steps=10, batch_size=32, log_every=5,
            checkpoint_dir=ckpt, checkpoint_every=5,
        ))
        result = run_evaluator(TrainArgs(
            model="mnist", steps=10, batch_size=32, checkpoint_dir=ckpt,
            eval_batches=2,
        ))
        assert result["final_step"] == 10
        assert "eval_loss" in result and np.isfinite(result["eval_loss"])


class TestTrainLoop:
    def test_loop_runs_hooks_and_counts_steps(self, caplog):
        state = make_linear_state()
        step = make_train_step(quadratic_loss, precision=FP32)
        data = iter(lambda: linear_batch(), None)  # infinite same batch

        loop = TrainLoop(
            step, state, data,
            hooks=[LoggingHook(every_steps=10), NanHook()],
            examples_per_step=64, metrics_every=5,
        )
        with caplog.at_level(logging.INFO):
            final = loop.run(20)
        assert int(jax.device_get(final.step)) == 20
        assert loop.last_logged_metrics.get("loss") is not None

    def test_nan_hook_raises(self):
        def bad_loss(params, batch, rng):
            return jnp.float32(jnp.nan), {}

        state = make_linear_state()
        step = make_train_step(bad_loss, precision=FP32)
        data = iter(lambda: linear_batch(), None)
        loop = TrainLoop(step, state, data, hooks=[NanHook()],
                         metrics_every=1)
        with pytest.raises(FloatingPointError):
            loop.run(3)


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        state = make_linear_state()
        step = make_train_step(quadratic_loss, precision=FP32)
        state, _ = step(state, linear_batch(), jax.random.key(0))

        mngr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        assert mngr.save(1, state)
        mngr.wait_until_finished()
        assert mngr.latest_step() == 1

        fresh = make_linear_state()
        restored = mngr.restore(template=fresh)
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), np.asarray(state.params["w"])
        )
        assert int(restored.step) == 1
        mngr.close()

    def test_restore_or_init_without_checkpoint(self, tmp_path):
        mngr = CheckpointManager(str(tmp_path / "empty"), async_save=False)
        state = make_linear_state()
        out = mngr.restore_or_init(state)
        assert out is state
        mngr.close()

    def test_max_to_keep(self, tmp_path):
        mngr = CheckpointManager(
            str(tmp_path / "gc"), max_to_keep=2, async_save=False
        )
        state = make_linear_state()
        for s in (1, 2, 3):
            mngr.save(s, state, force=True)
        mngr.wait_until_finished()
        assert list(mngr.all_steps()) == [2, 3]
        mngr.close()


class TestTrainLib:
    def test_mnist_end_to_end_loss_decreases(self, tmp_path):
        res = run(TrainArgs(
            model="mnist", steps=150, batch_size=64, log_every=50,
            learning_rate=3e-3, precision="fp32",
        ))
        assert res["final_step"] == 150
        assert res["loss"] < 2.0  # clearly better than uniform 10-class CE

    def test_mnist_sharded_over_mesh_axes(self):
        # data x fsdp mesh exercise on the virtual 8-device mesh.
        res = run(TrainArgs(
            model="mnist", steps=20, batch_size=64, data=4, fsdp=2,
            log_every=10, precision="fp32",
        ))
        assert res["final_step"] == 20

    def test_checkpoint_resume_continues_at_step(self, tmp_path):
        ckpt = str(tmp_path / "resume")
        run(TrainArgs(model="mnist", steps=30, batch_size=64,
                      checkpoint_dir=ckpt, checkpoint_every=10,
                      log_every=10, precision="fp32"))
        res = run(TrainArgs(model="mnist", steps=50, batch_size=64,
                            checkpoint_dir=ckpt, checkpoint_every=10,
                            log_every=10, precision="fp32"))
        assert res["final_step"] == 50

    def test_ps_task_parks_and_returns_nothing(self):
        import threading

        from distributed_tensorflow_tpu.cluster import server as server_mod

        # Run ps-role entrypoint in a thread; it parks in join().  We can't
        # easily shut it down through run()'s internals, so assert it is
        # still parked after a moment, then release it via the Server object.
        import json, os
        env_backup = os.environ.get("TF_CONFIG")
        os.environ["TF_CONFIG"] = json.dumps({
            "cluster": {"worker": ["localhost:1"], "ps": ["localhost:2"]},
            "task": {"type": "ps", "index": 0},
        })
        try:
            result = {}
            t = threading.Thread(
                target=lambda: result.update(run(TrainArgs(model="mnist"))),
                daemon=True,
            )
            t.start()
            t.join(timeout=1.0)
            assert t.is_alive()  # parked, as a TF ps would be
        finally:
            if env_backup is None:
                del os.environ["TF_CONFIG"]
            else:
                os.environ["TF_CONFIG"] = env_backup


class TestWorkloadRegistry:
    def test_mnist_registered(self):
        assert "mnist" in available_models()
        w = get_workload("mnist", batch_size=32)
        assert w.batch_size == 32

    def test_unknown_model_raises(self):
        with pytest.raises(ValueError):
            get_workload("alexnet")
