"""Seeded lock-order inversion: Alpha acquires Beta's lock while
holding its own, Beta acquires Alpha's the same way — a classic
two-lock deadlock the ``lock-order`` rule must report as a cycle."""

import threading


class Alpha:
    def __init__(self, beta: "Beta"):
        self._lock = threading.Lock()
        self.beta: "Beta" = beta
        self.steps = 0

    def step(self) -> None:
        with self._lock:
            self.beta.poke()  # SEED: acquires Beta._lock under Alpha._lock


class Beta:
    def __init__(self, alpha: "Alpha"):
        self._lock = threading.Lock()
        self.alpha: "Alpha" = alpha
        self.pokes = 0

    def poke(self) -> None:
        with self._lock:
            self.pokes += 1

    def kick(self) -> None:
        with self._lock:
            self.alpha.step()  # SEED: acquires Alpha._lock under Beta._lock
