"""Seeded gateway defects, one per rule family:

- ``StreamFanout`` writes ``pending`` on its pump thread and reads it
  from the main (HTTP writer) thread with no lock anywhere — the shape
  of a stream-queue depth counter shared between the decode loop and an
  SSE writer.  ``cross-thread-race`` must report the write site.
- ``SseWriter`` turns each decode step's device tokens into SSE payload
  floats with an implicit fetch (``float(tok[0])``) inside the hot
  launch loop — the accidental per-token device sync ``host-sync``
  exists to catch.

Lines are tagged ``# SEED: <rule-id>`` so each rule family only claims
its own lines when both run over this module.
"""

import threading

import jax

_launch_lock = threading.Lock()


class StreamFanout:
    def __init__(self):
        self.pending = 0
        self._thread = threading.Thread(target=self._pump, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _pump(self) -> None:
        while True:
            self.pending += 1  # SEED: cross-thread-race

    def depth(self) -> int:
        return self.pending


class SseWriter:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)

    def write_stream(self, tok, steps):
        events = []
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            events.append(float(tok[0]))  # SEED: host-sync
        return events
