"""Clean twin of ``blocking_bad``: the future is resolved OUTSIDE the
lock; only the cheap append runs under it."""

import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._out = []

    def drain(self, fut) -> None:
        value = fut.result()
        with self._lock:
            self._out.append(value)
