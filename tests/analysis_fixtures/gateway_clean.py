"""Clean twin of ``gateway_bad``: the pump-thread write and the
main-thread read of ``pending`` share one lock, and the SSE payload
fetch goes through ONE explicit ``jax.device_get`` point per step —
the sanctioned visible-fetch idiom.  Zero findings expected."""

import threading

import jax

_launch_lock = threading.Lock()


class StreamFanout:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0
        self._thread = threading.Thread(target=self._pump, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _pump(self) -> None:
        while True:
            with self._lock:
                self.pending += 1

    def depth(self) -> int:
        with self._lock:
            return self.pending


class SseWriter:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)

    def write_stream(self, tok, steps):
        events = []
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            host = jax.device_get(tok)
            events.append(float(host[0]))
        return events
