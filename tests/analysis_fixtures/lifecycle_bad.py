"""Seeded lifecycle-recorder defects, one per rule family:

- ``EventLog`` appends to its event list from the exporter's background
  drain thread and snapshots it from the main (stats) thread with no
  lock anywhere — the shape of a per-request lifecycle recorder shared
  between a JSONL export thread and the scheduler's ``stats()``.
  ``cross-thread-race`` must report the write site.
- ``DecodeLoop._record_token`` is the lifecycle tap gone wrong: it
  folds the freshly stepped DEVICE token into the breakdown with
  ``float(...)`` — an implicit per-iteration device sync smuggled in
  through an innocent-looking observability hook.  The real recorder
  (``obs/lifecycle.py``) takes HOST scalars the loop already fetched;
  ``host-sync`` exists to catch exactly this regression.

Lines are tagged ``# SEED: <rule-id>`` so each rule family only claims
its own lines when both run over this module.
"""

import threading

import jax

_launch_lock = threading.Lock()


class EventLog:
    def __init__(self):
        self.events = []
        self._thread = threading.Thread(target=self._drain, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _drain(self) -> None:
        while True:
            self.events += [("RETIRED", 0.0)]  # SEED: cross-thread-race

    def snapshot(self):
        return list(self.events)


class DecodeLoop:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)
        self._last_tok = None
        self.breakdown = []

    def _record_token(self) -> None:
        # Hot because decode's iteration loop calls it — and the value
        # it "just logs" is still resident on device.
        self.breakdown.append(float(self._last_tok[0]))  # SEED: host-sync

    def decode(self, tok, steps):
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            self._last_tok = tok
            self._record_token()
        return tok
