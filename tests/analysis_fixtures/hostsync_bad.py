"""Seeded hot-loop host syncs: device values implicitly fetched inside
the decode loop — ``float()`` / ``bool()`` on launch results, ``.item()``
in the retire walk, and a stats helper made hot by the CALL GRAPH (not a
name allowlist) reading a device-tainted attribute.  ``host-sync`` must
flag exactly the marked lines."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniSyncEngine:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)
        self._last = None

    def decode(self, tok, steps):
        total = 0.0
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            self._last = tok
            total += float(tok[0])  # SEED: host-sync
            total += self._flush_stats()
            if bool(tok[-1] == 0):  # SEED: host-sync
                break
        return total

    def _flush_stats(self):
        # Hot because decode's iteration loop calls it, not because of
        # its name.
        return float(self._last[0])  # SEED: host-sync

    def retire(self, tok_dev, n):
        outs = []
        while n > 0:
            with _launch_lock:
                tok_dev = self._step(self.params, tok_dev)
            outs.append(tok_dev.item())  # SEED: host-sync
            n -= 1
        return outs
