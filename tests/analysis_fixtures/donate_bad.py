"""Seeded device-boundary violations: a donated cache read after the
launch without rebinding (the engine's donated-cache chaining done
wrong), both through a local name and through ``self._cache``, plus a
jitted program that mutates-and-returns its cache parameter WITHOUT
donating it (the double-HBM footgun).  ``use-after-donate`` and
``donation-discipline`` must flag exactly the marked lines."""

import threading

import jax
import jax.numpy as jnp

_launch_lock = threading.Lock()


class MiniDonatingEngine:
    def __init__(self, module, params, cache):
        self.module = module
        self.params = params
        self._cache = cache
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))
        self._prefill = jax.jit(self._prefill_apply)  # SEED: donation-discipline

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def _prefill_apply(self, params, cache, tokens):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tokens, mutable=["cache"])
        return out, mutated["cache"]

    def generate(self, cache, tok, steps):
        for _ in range(steps):
            with _launch_lock:
                tok, _new = self._step(self.params, cache, tok)
            out = jnp.sum(cache)  # SEED: use-after-donate
        return out

    def refill(self, tokens):
        with _launch_lock:
            tok, _ = self._step(self.params, self._cache, tokens)
        return tok, jnp.sum(self._cache)  # SEED: use-after-donate
