"""Clean twin of ``lockorder_bad``: Beta calls back into Alpha OUTSIDE
its own lock, so the acquisition graph has one direction only and the
``lock-order`` rule must stay silent."""

import threading


class Alpha:
    def __init__(self, beta: "Beta"):
        self._lock = threading.Lock()
        self.beta: "Beta" = beta
        self.steps = 0

    def step(self) -> None:
        with self._lock:
            self.beta.poke()


class Beta:
    def __init__(self, alpha: "Alpha"):
        self._lock = threading.Lock()
        self.alpha: "Alpha" = alpha
        self.pokes = 0

    def poke(self) -> None:
        with self._lock:
            self.pokes += 1

    def kick(self) -> None:
        # Snapshot-then-call: no lock held across the foreign acquisition.
        with self._lock:
            self.pokes += 1
        self.alpha.step()
