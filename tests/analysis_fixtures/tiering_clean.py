"""Clean twin of ``tiering_bad``: the reclaim-thread write and the
stats-thread read of ``swapped_bytes`` share one lock, and the swap-out
payload fetch goes through ONE explicit ``jax.device_get`` point per
iteration — the sanctioned visible-fetch idiom ``serve/tiering.py``
itself uses.  Zero findings expected."""

import threading

import jax
import numpy as np

_launch_lock = threading.Lock()


class SwapLedger:
    def __init__(self):
        self._lock = threading.Lock()
        self.swapped_bytes = 0
        self._thread = threading.Thread(target=self._reclaim, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _reclaim(self) -> None:
        while True:
            with self._lock:
                self.swapped_bytes += 4096

    def resident(self) -> int:
        with self._lock:
            return self.swapped_bytes


class Preemptor:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, kv: kv)

    def decode_with_swap(self, kv, steps):
        payloads = []
        for _ in range(steps):
            with _launch_lock:
                kv = self._step(self.params, kv)
            host = jax.device_get(kv)
            payloads.append(np.asarray(host))
        return payloads
