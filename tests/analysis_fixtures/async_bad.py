"""Seeded async double-buffering violations: the dispatch half launches
megastep N+1 through the donated resident cache while megastep N is
still in flight — reading the pre-launch cache handle after dispatch
(``use-after-donate``, through a local pin and through ``self._cache``)
and ``float()``-ing the still-in-flight token array instead of waiting
for the fetch half (``host-sync``, a stall that serializes the overlap
the double buffer exists for).  Each rule must flag exactly its marked
lines."""

import threading

import jax
import jax.numpy as jnp

_launch_lock = threading.Lock()


class MiniAsyncEngine:
    def __init__(self, module, params, cache):
        self.module = module
        self.params = params
        self._cache = cache
        self._pending = None
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def decode(self, tok, steps):
        # Double-buffered loop done WRONG: the pre-launch cache handle
        # is pinned in a local, donated to the dispatch, then read —
        # its buffer now belongs to the in-flight launch.
        for _ in range(steps):
            cache = self._cache
            with _launch_lock:
                tok, self._cache = self._step(self.params, cache, tok)
            probe = jnp.sum(cache)  # SEED: use-after-donate
            if float(tok[0]) == 0:  # SEED: host-sync
                break
        return probe

    def drain(self, tok):
        # The drain launch donates self._cache but binds the result
        # elsewhere — the attribute still names the dead buffer.
        with _launch_lock:
            tok, fresh = self._step(self.params, self._cache, tok)
        self._pending = fresh
        return jnp.sum(self._cache)  # SEED: use-after-donate
