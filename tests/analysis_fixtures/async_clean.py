"""Clean twin of ``async_bad``: the same double-buffered shape, but the
donated cache is rebound in the SAME assignment as every launch (the
chaining idiom), the in-flight token is only touched through ONE
explicit ``jax.device_get`` after the next dispatch went out, and the
drain rebinds the attribute it donates.  Zero findings expected."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniAsyncEngine:
    def __init__(self, module, params, cache):
        self.module = module
        self.params = params
        self._cache = cache
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def decode(self, tok, steps):
        # Double-buffered: dispatch N+1 first, then resolve N's tokens
        # through the single sanctioned fetch point.
        prev = None
        for _ in range(steps):
            with _launch_lock:
                tok, self._cache = self._step(self.params, self._cache, tok)
            if prev is not None:
                host = jax.device_get(prev)
                if int(host[0]) == 0:
                    break
            prev = tok
        return jax.device_get(tok)

    def drain(self, tok):
        with _launch_lock:
            tok, self._cache = self._step(self.params, self._cache, tok)
        return int(jax.device_get(tok)[0])
