"""Clean twin of ``megastep_bad``: the K-step scan dispatch holds the
module-level launch lock (the ``serve.engine._launch_lock`` pattern),
serializing fused-decode launches across scheduler threads."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniEngine:
    def __init__(self):
        self._programs = {}
        self._programs["megastep"] = jax.jit(lambda tok: tok)

    def decode_megastep(self, tok):
        with _launch_lock:
            return self._programs["megastep"](tok)


class Scheduler:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        self.engine.decode_megastep(None)
