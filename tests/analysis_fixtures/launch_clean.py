"""Clean twin of ``launch_bad``: the dispatch holds a module-level
launch lock (the ``serve.engine._launch_lock`` pattern), serializing
collective launches across threads."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniEngine:
    def __init__(self):
        self._step_fn = jax.jit(lambda x: x)

    def run_step(self, batch):
        with _launch_lock:
            return self._step_fn(batch)


class Loop:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        self.engine.run_step(None)
