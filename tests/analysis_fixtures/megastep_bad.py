"""Seeded unlocked megastep launch: the K-step fused decode program
(a ``lax.scan`` over the decode iteration, cached in a program dict
keyed on K) dispatched from the scheduler's worker thread with no
module-level launch lock.  Two replicas scanning concurrently deadlock
in the XLA collective rendezvous just like single-step decode — the
scan body runs K collectives back-to-back, so the window is K times
wider.  ``collective-launch`` must flag the dispatch site."""

import threading

import jax


class MiniEngine:
    def __init__(self):
        self._programs = {}
        self._programs["megastep"] = jax.jit(lambda tok: tok)

    def decode_megastep(self, tok):
        return self._programs["megastep"](tok)  # SEED: scan launch without a launch lock


class Scheduler:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        self.engine.decode_megastep(None)
