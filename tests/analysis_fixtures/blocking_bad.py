"""Seeded blocking-call-under-lock: ``Future.result()`` awaited while
holding the collector's lock — every other holder stalls behind an
unbounded wait.  The ``lock-order`` warning tier must flag it."""

import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._out = []

    def drain(self, fut) -> None:
        with self._lock:
            value = fut.result()  # SEED: unbounded wait under self._lock
            self._out.append(value)
