"""Seeded fixture: per-request sampling config flowing into jit compile
caches — the antipattern the vectorized sampling path removes.  A
NON-frozen (mutable, unhashable-by-identity) config object lands in the
program-cache key or gets baked into the jitted callable itself, so
every distinct request config compiles (and leaks) its own program."""

import dataclasses
import functools

import jax


@dataclasses.dataclass
class SamplingConfig:
    """Mutable per-request config — exactly what must NOT key a program."""

    temperature: float = 0.0
    top_k: int = 0


def _apply(cfg, params, tokens):
    return tokens


class BadEngine:
    def __init__(self):
        self._cache = {}

    def decode_fn(self, cfg: SamplingConfig):
        # Per-request config in the compile-cache key: one compiled
        # program per distinct (mutated!) config object.
        key = ("slot_decode", cfg)
        self._cache[key] = jax.jit(_apply)  # SEED: recompile-hazard
        return self._cache[key]

    def prefill_fn(self, cfg: SamplingConfig):
        self._cache[("slot_prefill", cfg)] = jax.jit(  # SEED: recompile-hazard
            _apply)
        return self._cache[("slot_prefill", cfg)]

    def verify_fn(self, cfg: SamplingConfig):
        # Baking the mutable config into the jitted callable is the same
        # hazard without a dict: a fresh partial per request is a fresh
        # program.
        fn = jax.jit(functools.partial(_apply, cfg))  # SEED: recompile-hazard
        return fn
