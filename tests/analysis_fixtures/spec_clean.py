"""Clean twin of ``spec_bad``: the speculative-verify dispatch holds
the module-level launch lock (the ``serve.engine._launch_lock``
pattern), serializing verify launches across scheduler threads."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniEngine:
    def __init__(self):
        self._programs = {}
        self._programs["slot_verify"] = jax.jit(lambda toks: toks)

    def verify_slots(self, toks):
        with _launch_lock:
            return self._programs["slot_verify"](toks)


class Scheduler:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        self.engine.verify_slots(None)
