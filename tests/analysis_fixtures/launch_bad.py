"""Seeded unlocked collective launch: a jitted program dispatched from
a worker thread with no module-level launch lock — two such threads
deadlock in the XLA collective rendezvous (the PR 7 bug).
``collective-launch`` must flag the dispatch site."""

import threading

import jax


class MiniEngine:
    def __init__(self):
        self._step_fn = jax.jit(lambda x: x)

    def run_step(self, batch):
        return self._step_fn(batch)  # SEED: launch without a launch lock


class Loop:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        self.engine.run_step(None)
