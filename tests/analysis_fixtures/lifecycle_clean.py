"""Clean twin of ``lifecycle_bad``: the drain-thread append and the
stats-thread snapshot of the event list share one lock, and the
lifecycle tap records only HOST scalars fetched through ONE explicit
``jax.device_get`` point per iteration — the sanctioned tap discipline
``obs/lifecycle.py`` documents.  Zero findings expected."""

import threading

import jax

_launch_lock = threading.Lock()


class EventLog:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []
        self._thread = threading.Thread(target=self._drain, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _drain(self) -> None:
        while True:
            with self._lock:
                self.events += [("RETIRED", 0.0)]

    def snapshot(self):
        with self._lock:
            return list(self.events)


class DecodeLoop:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)
        self.breakdown = []

    def _record_token(self, host_tok) -> None:
        # The hook takes a HOST scalar the loop already fetched.
        self.breakdown.append(float(host_tok))

    def decode(self, tok, steps):
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            host = jax.device_get(tok)
            self._record_token(host[0])
        return tok
