"""Clean twin of ``asyncring_bad``: the same depth-D launch ring +
fetch thread shape, done to the shipped discipline — the donated cache
is rebound in the SAME assignment as every launch (the chaining idiom),
ring records carry only launch OUTPUTS behind a ``Future`` the fetch
thread resolves through the single sanctioned ``jax.device_get``, the
scheduling thread drains oldest-first and only ever touches resolved
host values, and the fetch thread launches nothing.  Zero findings
expected."""

import collections
import queue
import threading
from concurrent.futures import Future

import jax

_launch_lock = threading.Lock()


class MiniRingEngine:
    def __init__(self, module, params, cache, depth=4):
        self.module = module
        self.params = params
        self._cache = cache
        self.depth = depth
        self._ring = collections.deque()
        self._fetch_q = queue.Queue()
        self._fetch_thread = threading.Thread(
            target=self._fetch_worker, daemon=True)
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def start(self):
        self._fetch_thread.start()

    def decode(self, tok, steps):
        # Depth-D ring: dispatch up to depth-1 launches ahead, enqueue
        # each output for the fetch thread, resolve strictly oldest-
        # first through the record's Future — the scheduling thread
        # never host-syncs an in-flight device value.
        out = None
        for _ in range(steps):
            with _launch_lock:
                tok, self._cache = self._step(
                    self.params, self._cache, tok)
            fut = Future()
            self._fetch_q.put((tok, fut))
            self._ring.append(fut)
            while len(self._ring) >= self.depth:
                out = self._ring.popleft().result()
                if int(out[0]) == 0:
                    return out
        while self._ring:
            out = self._ring.popleft().result()
        return out

    def close(self):
        self._fetch_q.put(None)
        self._fetch_thread.join()

    def _fetch_worker(self):
        # The fetch half: one ``jax.device_get`` per record, nothing
        # that compiles or launches.
        while True:
            rec = self._fetch_q.get()
            if rec is None:
                return
            tok, fut = rec
            fut.set_result(jax.device_get(tok))
