"""Seeded cross-thread race: ``value`` is written on the counter's own
daemon thread and read from the main thread with no lock anywhere —
the shape of the fleet ``_active`` bug.  ``cross-thread-race`` must
report the write site."""

import threading


class Counter:
    def __init__(self):
        self.value = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        while True:
            self.value += 1  # SEED: written on the counter thread, unlocked

    def read(self) -> int:
        return self.value
