"""Seeded-defect fixtures for the dttlint concurrency rules.

Each ``*_bad`` module plants exactly the defect its twin rule must
catch; each ``*_clean`` module is the same shape with the defect fixed
and must produce ZERO findings.  These modules are analyzed as source
by ``tests/test_analysis_concurrency.py`` — they are never imported at
runtime, and they are deliberately outside the analyzer's default
target set so the tree-wide gate stays clean.
"""
