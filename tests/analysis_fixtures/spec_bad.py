"""Seeded unlocked speculative-verify launch: the (num_slots, k+1)
verify program (cached in a program dict keyed on k) dispatched from the
scheduler's worker thread with no module-level launch lock.  Two
replicas verifying concurrently deadlock in the XLA collective
rendezvous exactly like single-step decode — the verify forward runs
the full layer stack's collectives for k+1 positions at once.
``collective-launch`` must flag the dispatch site."""

import threading

import jax


class MiniEngine:
    def __init__(self):
        self._programs = {}
        self._programs["slot_verify"] = jax.jit(lambda toks: toks)

    def verify_slots(self, toks):
        return self._programs["slot_verify"](toks)  # SEED: verify launch without a launch lock


class Scheduler:
    def __init__(self, engine: "MiniEngine"):
        self.engine: "MiniEngine" = engine
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        self.engine.verify_slots(None)
