"""Clean twin of ``donate_bad``: identical shape, but every donated
cache is rebound to the call's result (the documented chaining idiom)
and the mutating prefill program donates its cache argument.  Zero
findings expected from ``use-after-donate`` and
``donation-discipline``."""

import threading

import jax
import jax.numpy as jnp

_launch_lock = threading.Lock()


class MiniDonatingEngine:
    def __init__(self, module, params, cache):
        self.module = module
        self.params = params
        self._cache = cache
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))
        self._prefill = jax.jit(self._prefill_apply, donate_argnums=(1,))

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def _prefill_apply(self, params, cache, tokens):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tokens, mutable=["cache"])
        return out, mutated["cache"]

    def generate(self, cache, tok, steps):
        for _ in range(steps):
            with _launch_lock:
                tok, cache = self._step(self.params, cache, tok)
            out = jnp.sum(cache)
        return out

    def refill(self, tokens):
        with _launch_lock:
            tok, self._cache = self._step(self.params, self._cache, tokens)
        return tok, jnp.sum(self._cache)
