"""Clean twin of ``sampling_bad``: sampling config is request STATE,
not program identity.  The frozen params object is hashable and never
reaches a compile cache — per-request values ride into ONE jitted
program as runtime ``(num_slots,)`` vectors (the ``serve.sampling``
pattern), so the cache keys are static family tags."""

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0


def _apply(params, tokens, temperature, top_k):
    scaled = tokens / jnp.where(temperature > 0, temperature, 1.0)[:, None]
    return jnp.where(temperature <= 0, jnp.argmax(tokens, -1),
                     jnp.argmax(scaled, -1))


class CleanEngine:
    def __init__(self):
        self._cache = {}

    def decode_fn(self):
        # Static family tag: every request config shares this program.
        key = ("slot_decode",)
        if key not in self._cache:
            self._cache[key] = jax.jit(_apply)
        return self._cache[key]

    def launch(self, params, tokens, requests):
        # Per-request values become runtime vectors — never a key.
        temperature = jnp.asarray([r.temperature for r in requests])
        top_k = jnp.asarray([r.top_k for r in requests])
        return self.decode_fn()(params, tokens, temperature, top_k)
