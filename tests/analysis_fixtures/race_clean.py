"""Clean twin of ``race_bad``: both the counter-thread write and the
main-thread read hold the same lock, so every access shares a lock
group and the rule must stay silent."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                self.value += 1

    def read(self) -> int:
        with self._lock:
            return self.value
