"""Seeded KV-tiering defects, one per rule family:

- ``SwapLedger`` accumulates ``swapped_bytes`` on its background
  reclaim thread and reads it from the main (stats) thread with no
  lock anywhere — the shape of a host-tier residency gauge shared
  between a reclaimer and the scheduler's ``stats()``.
  ``cross-thread-race`` must report the write site.
- ``Preemptor`` pulls a victim's KV block to host with an implicit
  fetch (``np.asarray(kv)``) inside the hot decode loop — the
  accidental per-iteration device sync that a swap-out path invites
  when it skips the explicit ``jax.device_get`` boundary.

Lines are tagged ``# SEED: <rule-id>`` so each rule family only claims
its own lines when both run over this module.
"""

import threading

import jax
import numpy as np

_launch_lock = threading.Lock()


class SwapLedger:
    def __init__(self):
        self.swapped_bytes = 0
        self._thread = threading.Thread(target=self._reclaim, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _reclaim(self) -> None:
        while True:
            self.swapped_bytes += 4096  # SEED: cross-thread-race

    def resident(self) -> int:
        return self.swapped_bytes


class Preemptor:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, kv: kv)

    def decode_with_swap(self, kv, steps):
        payloads = []
        for _ in range(steps):
            with _launch_lock:
                kv = self._step(self.params, kv)
            payloads.append(np.asarray(kv))  # SEED: host-sync
        return payloads
