"""Clean twin of ``hostsync_bad``: the same hot loops, but every host
read goes through ONE explicit ``jax.device_get`` fetch point — the
sanctioned idiom ``host-sync`` documents (the fetch is visible and
batched, never an accidental implicit sync).  Zero findings expected."""

import threading

import jax

_launch_lock = threading.Lock()


class MiniSyncEngine:
    def __init__(self, params):
        self.params = params
        self._step = jax.jit(lambda params, tok: tok)
        self._last = None

    def decode(self, tok, steps):
        total = 0.0
        for _ in range(steps):
            with _launch_lock:
                tok = self._step(self.params, tok)
            self._last = tok
            host = jax.device_get(tok)
            total += float(host[0])
            total += self._flush_stats()
            if bool(host[-1] == 0):
                break
        return total

    def _flush_stats(self):
        host = jax.device_get(self._last)
        return float(host[0])

    def retire(self, tok_dev, n):
        outs = []
        while n > 0:
            with _launch_lock:
                tok_dev = self._step(self.params, tok_dev)
            outs.append(int(jax.device_get(tok_dev)[0]))
            n -= 1
        return outs
