"""Seeded deep-async launch-ring violations: the depth-D ring + fetch
thread idiom done WRONG three ways — the dispatch half pins the
pre-launch cache handle in a local across its own donation and reads it
after the launch went out (``use-after-donate``), drains the ring by
``float()``-ing the newest still-in-flight token on the scheduling
thread instead of letting the fetch thread resolve the oldest record
(``host-sync``, the stall that serializes the whole pipeline), and the
fetch thread "recomputes" a lost fetch by re-launching a jitted program
itself — a compiled-program launch from a worker thread with no
module-level launch lock (``collective-launch``, the XLA-rendezvous
deadlock).  Each rule must flag exactly its marked lines."""

import collections
import queue
import threading

import jax
import jax.numpy as jnp

_launch_lock = threading.Lock()


class MiniRingEngine:
    def __init__(self, module, params, cache, depth=4):
        self.module = module
        self.params = params
        self._cache = cache
        self.depth = depth
        self._ring = collections.deque()
        self._fetch_q = queue.Queue()
        self._fetch_thread = threading.Thread(
            target=self._fetch_worker, daemon=True)
        self._step = jax.jit(self._decode_apply, donate_argnums=(1,))
        self._redo = jax.jit(self._logits_apply)

    def _decode_apply(self, params, cache, tok):
        out, mutated = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out, mutated["cache"]

    def _logits_apply(self, params, cache, tok):
        out, _ = self.module.apply(
            {"params": params, "cache": cache}, tok, mutable=["cache"])
        return out

    def start(self):
        self._fetch_thread.start()

    def decode(self, tok, steps):
        # Depth-D ring done WRONG: the pre-launch cache handle is
        # pinned in a local, donated to the dispatch, then read — its
        # buffer now belongs to the in-flight launch — and the drain
        # host-syncs the NEWEST launch's token mid-loop instead of
        # handing the oldest record to the fetch thread.
        checksum = None
        for _ in range(steps):
            held = self._cache
            with _launch_lock:
                tok, self._cache = self._step(self.params, held, tok)
            self._ring.append(tok)
            if len(self._ring) >= self.depth:
                self._ring.popleft()
            checksum = jnp.sum(held)  # SEED: use-after-donate
            if float(tok[0]) == 0:  # SEED: host-sync
                break
        return checksum

    def _fetch_worker(self):
        # "Recovers" a lost fetch by RE-LAUNCHING a jitted program from
        # the fetch thread: a compiled launch off the loop thread with
        # no module-level launch lock — the fetch thread's one job is
        # ``jax.device_get``, never anything that compiles or launches.
        while True:
            rec = self._fetch_q.get()
            if rec is None:
                return
            tok, fut = rec
            p = self.params
            c = self._cache
            out = self._redo(p, c, tok)  # SEED: collective-launch
            fut.set_result(jax.device_get(out))
