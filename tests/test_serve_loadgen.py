"""Open-loop load-harness tests: trace construction is deterministic and
scenario-shaped, ``run_trace`` stays open-loop (shed never blocks the
arrival clock, 429s count against goodput), and the real-engine smoke
drives a tiny continuous scheduler end to end with the lifecycle
recorder attached.

The pure-host cases (trace building, spec parsing, fake-backend
scoring) are tier-1 cheap; the engine smoke rides the shared module
``gpt2_engine`` the other serve suites already pay for.
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from distributed_tensorflow_tpu.serve import ServeEngine
from distributed_tensorflow_tpu.serve.batcher import ServeOverloadedError
from distributed_tensorflow_tpu.serve.loadgen import (
    TraceRequest,
    build_trace,
    parse_trace_spec,
    run_trace,
    tier_name,
)

VOCAB = 64


@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


class TestBuildTrace:
    def test_same_seed_same_trace(self):
        a = build_trace(40, seed=3, vocab=VOCAB)
        b = build_trace(40, seed=3, vocab=VOCAB)
        assert len(a) == len(b) == 40
        for ra, rb in zip(a, b):
            assert ra.at == rb.at
            assert np.array_equal(ra.prompt, rb.prompt)
            assert (ra.scenario, ra.priority, ra.group, ra.turn) == \
                (rb.scenario, rb.priority, rb.group, rb.turn)

    def test_different_seed_differs(self):
        a = build_trace(40, seed=3, vocab=VOCAB)
        b = build_trace(40, seed=4, vocab=VOCAB)
        assert any(not np.array_equal(ra.prompt, rb.prompt)
                   for ra, rb in zip(a, b))

    @pytest.mark.parametrize("process", ["poisson", "diurnal", "burst"])
    def test_arrivals_sorted_and_positive(self, process):
        trace = build_trace(32, seed=1, process=process, rate=20.0,
                            vocab=VOCAB)
        ats = [r.at for r in trace]
        assert ats == sorted(ats)
        assert all(t >= 0.0 for t in ats)

    def test_chat_turns_resubmit_grown_prefix(self):
        trace = build_trace(60, seed=7, vocab=VOCAB, chat_frac=0.9,
                            whale_frac=0.0, shared_frac=0.0)
        convs = {}
        for r in trace:
            if r.scenario == "chat":
                convs.setdefault(r.group, []).append(r)
        assert convs, "no chat conversations drawn"
        grown = 0
        for turns in convs.values():
            turns.sort(key=lambda r: r.turn)
            for prev, nxt in zip(turns, turns[1:]):
                assert len(nxt.prompt) > len(prev.prompt)
                assert np.array_equal(nxt.prompt[:len(prev.prompt)],
                                      prev.prompt)
                grown += 1
        assert grown > 0

    def test_shared_groups_share_prefix(self):
        trace = build_trace(60, seed=9, vocab=VOCAB, shared_frac=0.9,
                            whale_frac=0.0, chat_frac=0.0, short_len=8)
        groups = {}
        for r in trace:
            if r.scenario == "shared":
                groups.setdefault(r.group, []).append(r)
        multi = [g for g in groups.values() if len(g) > 1]
        assert multi, "no multi-member shared groups drawn"
        for members in multi:
            head = members[0].prompt[:8]
            assert all(np.array_equal(m.prompt[:8], head)
                       for m in members)

    def test_tier_deadlines_applied(self):
        trace = build_trace(64, seed=5, vocab=VOCAB)
        for r in trace:
            tier = tier_name(r.priority)
            if tier == "batch":
                assert r.ttft_deadline_ms is None
            else:
                assert r.ttft_deadline_ms > 0
            assert r.tpot_deadline_ms > 0

    def test_max_total_len_clamps_prompts(self):
        trace = build_trace(64, seed=5, vocab=VOCAB, whale_frac=0.5,
                            whale_len=64, whale_new=16, max_total_len=32)
        assert all(len(r.prompt) + 0 <= 32 - r.max_new_tokens
                   or len(r.prompt) == 1 for r in trace)
        assert all(len(r.prompt) >= 1 for r in trace)


class TestParseTraceSpec:
    def test_defaults_and_overrides(self):
        kw = parse_trace_spec("poisson:n=24,rate=12,whale_frac=0.3",
                              rate=8.0, seed=2)
        assert kw["process"] == "poisson"
        assert kw["n"] == 24 and kw["rate"] == 12
        assert kw["whale_frac"] == pytest.approx(0.3)
        assert kw["seed"] == 2

    def test_bare_process_uses_argument_rate(self):
        kw = parse_trace_spec("burst", rate=5.0, seed=0)
        assert kw["process"] == "burst" and kw["rate"] == 5.0
        assert kw["n"] == 64

    def test_bad_pair_raises(self):
        with pytest.raises(ValueError, match="key=value"):
            parse_trace_spec("poisson:rate")

    def test_unknown_process_raises_at_build(self):
        kw = parse_trace_spec("sawtooth:n=4")
        n = kw.pop("n")
        with pytest.raises(ValueError, match="arrival process"):
            build_trace(n, **kw)


class _FakeBackend:
    """Scriptable backend: sheds every ``shed_every``-th submission and
    streams ``new`` tokens immediately for the rest."""

    def __init__(self, *, shed_every=0, new=3):
        self.shed_every = shed_every
        self.new = new
        self.submissions = 0
        self.sampling_seen = []

    def submit(self, prompt, *, max_new_tokens, sampling=None,
               on_token=None):
        self.submissions += 1
        if self.shed_every and self.submissions % self.shed_every == 0:
            raise ServeOverloadedError("queue full; back off and retry")
        self.sampling_seen.append(dict(sampling or {}))
        toks = list(range(self.new))
        if on_token is not None:
            on_token(toks)
        fut = Future()
        fut.set_result(np.asarray(toks, np.int32))
        return fut


class TestRunTraceOpenLoop:
    def _trace(self, n=12, rate=500.0):
        return build_trace(n, seed=1, rate=rate, vocab=VOCAB)

    def test_shed_counts_against_goodput_and_never_blocks(self):
        backend = _FakeBackend(shed_every=3)
        trace = self._trace(12)
        t0 = time.monotonic()
        report = run_trace(backend, trace, speed=1e4)
        assert time.monotonic() - t0 < 10.0
        assert report["requests_total"] == 12
        assert report["shed"] == 4
        assert report["shed_rate"] == pytest.approx(4 / 12)
        # Every non-shed request completed instantly -> met its SLO.
        assert report["completed"] == 8
        assert report["goodput_under_slo"] == pytest.approx(8 / 12)

    def test_priority_and_deadline_ride_sampling(self):
        backend = _FakeBackend()
        run_trace(backend, self._trace(10), speed=1e4)
        assert len(backend.sampling_seen) == 10
        assert all("priority" in s for s in backend.sampling_seen)
        assert any("deadline_ms" in s for s in backend.sampling_seen)

    def test_tokens_checksum_stable_across_replays(self):
        trace = self._trace(10)
        a = run_trace(_FakeBackend(), trace, speed=1e4)
        b = run_trace(_FakeBackend(), trace, speed=1e4)
        assert a["tokens_checksum"] == b["tokens_checksum"]
        c = run_trace(_FakeBackend(new=4), trace, speed=1e4)
        assert c["tokens_checksum"] != a["tokens_checksum"]

    def test_report_schema(self):
        report = run_trace(_FakeBackend(), self._trace(8), speed=1e4)
        for key in ("requests_total", "completed", "shed", "errors",
                    "shed_rate", "goodput_under_slo", "tokens_emitted",
                    "wall_s", "tokens_per_sec", "client_ttft_p50_ms",
                    "client_ttft_p99_ms", "tokens_checksum", "by_tier",
                    "by_scenario"):
            assert key in report, key
        assert sum(report["by_scenario"].values()) == 8

    def test_speed_must_be_positive(self):
        with pytest.raises(ValueError, match="speed"):
            run_trace(_FakeBackend(), self._trace(2), speed=0.0)


class TestEngineSmoke:
    def test_trace_drives_scheduler_with_lifecycle(self, gpt2_engine):
        from distributed_tensorflow_tpu.obs.lifecycle import (
            LifecycleRecorder,
        )
        from distributed_tensorflow_tpu.obs.metrics import Registry
        from distributed_tensorflow_tpu.serve import ContinuousScheduler

        vocab = gpt2_engine.module.cfg.vocab_size
        trace = build_trace(6, seed=13, rate=100.0, vocab=vocab,
                            short_len=4, short_new=4, whale_frac=0.0,
                            chat_frac=0.0, shared_frac=0.0,
                            max_total_len=16)
        rec = LifecycleRecorder(registry=Registry())
        sched = ContinuousScheduler(gpt2_engine, num_slots=2,
                                    max_total_len=16, lifecycle=rec)
        try:
            report = run_trace(sched, trace, speed=1e3, lifecycle=rec)
        finally:
            sched.close()
            rec.close()
            gpt2_engine.set_lifecycle(None)
        assert report["completed"] == 6 and report["shed"] == 0
        assert report["tokens_emitted"] == 6 * 4
        lc = report["lifecycle"]
        assert lc["lifecycle_requests_total"] == 6.0
        assert lc["breakdown_sum_to_wall_ratio"] == pytest.approx(
            1.0, abs=0.05)
        walls = rec.breakdowns()
        assert len(walls) == 6
        for b in walls:
            parts = sum(b[p] for p in ("queue_wait", "prefill",
                                       "decode_compute", "fetch_wait",
                                       "swap", "scheduler_stall"))
            assert parts == pytest.approx(b["wall"], abs=0.005)
