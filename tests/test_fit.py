"""TF2 ``Model.fit`` surface tests: the keras-shaped port target (SURVEY.md
§2 L6's last row).  A TF2 script's ``model.fit(dataset, epochs=,
callbacks=)`` call must work unchanged over the TPU-native loop."""

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.compat.fit import (
    Callback,
    EarlyStopping,
    History,
    Model,
)


class RecordingCallback(Callback):
    def __init__(self):
        self.events = []

    def on_train_begin(self, logs=None):
        self.events.append("train_begin")

    def on_epoch_begin(self, epoch, logs=None):
        self.events.append(("epoch_begin", epoch))

    def on_epoch_end(self, epoch, logs=None):
        self.events.append(("epoch_end", epoch, dict(logs or {})))

    def on_train_end(self, logs=None):
        self.events.append("train_end")


class TestFit:
    def test_fit_trains_and_returns_history(self):
        model = Model("mnist", batch_size=32)
        model.compile(learning_rate=1e-3)
        history = model.fit(epochs=2, steps_per_epoch=10)
        assert isinstance(history, History)
        assert history.epoch == [0, 1]
        assert len(history.history["loss"]) == 2
        assert all(np.isfinite(v) for v in history.history["loss"])
        assert int(jax.device_get(model.state.step)) == 20
        # a second fit continues from the trained state
        model.fit(epochs=1, steps_per_epoch=5)
        assert int(jax.device_get(model.state.step)) == 25

    def test_callbacks_and_validation(self):
        model = Model("mnist", batch_size=32)
        cb = RecordingCallback()
        history = model.fit(
            epochs=2, steps_per_epoch=10, callbacks=[cb],
            validation_data=model.workload.data_fn, validation_steps=2,
        )
        assert cb.events[0] == "train_begin"
        assert cb.events[-1] == "train_end"
        epoch_ends = [e for e in cb.events
                      if isinstance(e, tuple) and e[0] == "epoch_end"]
        assert len(epoch_ends) == 2
        assert "val_loss" in epoch_ends[0][2]
        assert "val_loss" in history.history
        assert np.isfinite(history.history["val_loss"][0])

    def test_finite_one_shot_validation_iterator_fails_loudly(self):
        """A FINITE generator as validation_data exhausts after epoch 1;
        val_ metrics must not silently vanish (keras re-iterates per
        epoch) — loud error instead.  An INFINITE generator (the synthetic
        data_fn stream) keeps working."""
        model = Model("mnist", batch_size=32)
        batches = [next(model.workload.data_fn(32)) for _ in range(2)]
        finite = iter(batches)
        with pytest.raises(ValueError, match="re-iterable"):
            model.fit(epochs=2, steps_per_epoch=2, validation_data=finite,
                      validation_steps=2)

        infinite = Model("mnist", batch_size=32)
        history = infinite.fit(
            epochs=2, steps_per_epoch=2,
            validation_data=infinite.workload.data_fn(32),
            validation_steps=2,
        )
        assert len(history.history["val_loss"]) == 2

    def test_early_stopping_stops_training(self):
        model = Model("mnist", batch_size=32)
        # patience=0 on a metric that cannot improve -> stops after epoch 2
        stopper = EarlyStopping(monitor="loss", patience=0,
                                min_delta=1e9)
        history = model.fit(epochs=10, steps_per_epoch=5,
                            callbacks=[stopper])
        assert len(history.epoch) == 2  # epoch 0 sets best; epoch 1 stops
        assert int(jax.device_get(model.state.step)) == 10

    def test_evaluate_returns_finite_metrics(self):
        model = Model("mnist", batch_size=32)
        model.fit(epochs=1, steps_per_epoch=5)
        metrics = model.evaluate(steps=3)
        assert "loss" in metrics and np.isfinite(metrics["loss"])

    def test_evaluate_before_fit_does_not_lock_schedule(self):
        """evaluate() builds with a placeholder horizon; a later fit() must
        rebuild the LR schedule around the real horizon (a schedule built
        for 3 steps would be fully decayed to ~0 LR — params frozen)."""
        model = Model("mnist", batch_size=32)
        model.evaluate(steps=3)
        w_before = np.asarray(jax.device_get(
            jax.tree.leaves(model.state.params)[0])).copy()
        model.fit(epochs=1, steps_per_epoch=10)
        w_after = np.asarray(jax.device_get(
            jax.tree.leaves(model.state.params)[0]))
        assert int(jax.device_get(model.state.step)) == 10
        assert np.abs(w_after - w_before).max() > 1e-6

    def test_save_and_load_weights_roundtrip(self, tmp_path):
        model = Model("mnist", batch_size=32)
        model.fit(epochs=1, steps_per_epoch=5)
        model.save_weights(str(tmp_path / "w"))
        w = np.asarray(jax.device_get(
            jax.tree.leaves(model.state.params)[0]))

        other = Model("mnist", batch_size=32)
        other.load_weights(str(tmp_path / "w"))
        w2 = np.asarray(jax.device_get(
            jax.tree.leaves(other.state.params)[0]))
        np.testing.assert_array_equal(w, w2)
        assert int(jax.device_get(other.state.step)) == 5

    def test_fit_after_load_weights_keeps_restored_opt_state(self, tmp_path):
        """Resume parity: fit() after load_weights() of a mid-training
        checkpoint must carry the restored optimizer state through the
        real-horizon rebuild — a fresh opt_state would silently reset
        Adam's moments and the schedule position.  Every scalar count in
        adamw's opt_state tracks the step, so after 4 + 4 steps they all
        read 8 (a reset would leave them at 4)."""
        model = Model("mnist", batch_size=32)
        model.fit(epochs=1, steps_per_epoch=4)
        model.save_weights(str(tmp_path / "w"))

        resumed = Model("mnist", batch_size=32)
        resumed.load_weights(str(tmp_path / "w"))
        resumed.fit(epochs=1, steps_per_epoch=4)
        assert int(jax.device_get(resumed.state.step)) == 8
        counts = [int(jax.device_get(leaf))
                  for leaf in jax.tree.leaves(resumed.state.opt_state)
                  if np.asarray(jax.device_get(leaf)).ndim == 0]
        assert counts, "adamw opt_state should carry scalar step counts"
        assert all(c == 8 for c in counts), counts

    def test_multihost_global_batched_dataset_fails_loudly(self, monkeypatch):
        """On >1 hosts a pre-built (usually GLOBAL-batched) dataset whose
        first batch doesn't match the per-host size must raise — pointing
        at data.tf_dataset_data_fn — not warn and desync."""
        model = Model("mnist", batch_size=32)

        class FakeDataset:
            """Duck-typed tf.data.Dataset yielding GLOBAL batches of 64."""

            def shard(self, num_shards, index):
                return self

            def as_numpy_iterator(self):
                rng = np.random.RandomState(0)
                while True:
                    yield {"image": rng.rand(64, 28, 28, 1)
                           .astype(np.float32),
                           "label": np.zeros((64,), np.int32)}

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="tf_dataset_data_fn"):
            next(model._host_iter(FakeDataset()))

    def test_fit_call_ports_intact_from_tf_dataset(self):
        """The migration story: a reference TF2 script's dataset feeds
        fit() unchanged through the tf.data adapter."""
        tf = pytest.importorskip("tensorflow")
        rng = np.random.RandomState(0)
        images = rng.rand(64, 28, 28, 1).astype(np.float32)
        labels = rng.randint(0, 10, size=(64,)).astype(np.int32)
        ds = tf.data.Dataset.from_tensor_slices(
            ({"image": images}, labels)
        ).repeat().batch(32)

        model = Model("mnist", batch_size=32)
        history = model.fit(ds, epochs=1, steps_per_epoch=6)
        assert np.isfinite(history.history["loss"][0])
        assert int(jax.device_get(model.state.step)) == 6
