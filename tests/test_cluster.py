"""Tests for cluster spec / resolvers / topology (SURVEY.md §3.3 parity)."""

import json

import jax
import pytest

from distributed_tensorflow_tpu.cluster import (
    ClusterSpec,
    MeshConfig,
    SimpleClusterResolver,
    Server,
    TFConfigClusterResolver,
    Topology,
    build_mesh,
    resolve,
    single_axis_mesh,
)


class TestClusterSpec:
    def test_from_dict_lists(self):
        spec = ClusterSpec({"ps": ["ps0:2222", "ps1:2222"],
                            "worker": ["w0:2222", "w1:2222", "w2:2222"]})
        assert spec.jobs == ["ps", "worker"]
        assert spec.num_tasks("worker") == 3
        assert spec.task_address("ps", 1) == "ps1:2222"
        assert spec.job_tasks("worker") == ["w0:2222", "w1:2222", "w2:2222"]

    def test_from_dict_mapping_and_roundtrip(self):
        spec = ClusterSpec({"worker": {0: "a:1", 2: "c:3"}})
        assert spec.task_indices("worker") == [0, 2]
        assert spec.as_dict() == {"worker": {0: "a:1", 2: "c:3"}}
        assert ClusterSpec(spec) == spec

    def test_unknown_job_raises(self):
        spec = ClusterSpec({"worker": ["w0:1"]})
        with pytest.raises(ValueError):
            spec.num_tasks("ps")
        with pytest.raises(ValueError):
            spec.task_address("worker", 5)

    def test_process_mapping_excludes_ps(self):
        spec = ClusterSpec({"chief": ["c:1"], "worker": ["w0:1", "w1:1"],
                            "ps": ["p0:1"]})
        assert spec.num_processes() == 3
        assert spec.process_id("chief", 0) == 0
        assert spec.process_id("worker", 1) == 2
        assert spec.process_id("ps", 0) == -1
        assert spec.coordinator_address() == "c:1"

    def test_process_id_sparse_indices_match_compute_tasks(self):
        spec = ClusterSpec({"chief": ["c:1"], "worker": {0: "w0:1", 2: "w2:1"}})
        assert spec.num_processes() == 3
        # Ranks must be dense 0..n-1 in compute_tasks() order.
        assert spec.process_id("chief", 0) == 0
        assert spec.process_id("worker", 0) == 1
        assert spec.process_id("worker", 2) == 2

    def test_process_id_absent_task_raises(self):
        spec = ClusterSpec({"worker": ["w0:1", "w1:1"]})
        with pytest.raises(ValueError):
            spec.process_id("chief", 0)
        with pytest.raises(ValueError):
            spec.process_id("worker", 5)


class TestResolvers:
    def test_tf_config_resolver(self):
        env = {"TF_CONFIG": json.dumps({
            "cluster": {"worker": ["w0:1", "w1:1"]},
            "task": {"type": "worker", "index": 1},
        })}
        r = TFConfigClusterResolver(environ=env)
        assert r.task_type == "worker"
        assert r.task_id == 1
        assert r.cluster_spec().num_tasks("worker") == 2
        assert r.process_id() == 1
        assert r.num_processes() == 2
        assert r.master() == "w0:1"

    def test_empty_tf_config_is_single_process(self):
        r = TFConfigClusterResolver(environ={})
        assert not r.cluster_spec()
        assert r.num_processes() == 1
        assert r.process_id() == 0

    def test_flag_override(self):
        env = {"TF_CONFIG": json.dumps({
            "cluster": {"worker": ["w0:1", "w1:1"]},
            "task": {"type": "worker", "index": 0},
        })}
        r = TFConfigClusterResolver(task_type="worker", task_id=1, environ=env)
        assert r.task_id == 1

    def test_simple_resolver_ps_not_compute(self):
        spec = ClusterSpec({"worker": ["w:1"], "ps": ["p:1"]})
        r = SimpleClusterResolver(spec, task_type="ps", task_id=0)
        assert not r.is_compute_task()

    def test_resolve_single_process_default(self):
        r = resolve()
        assert r.num_processes() >= 1


class TestServer:
    def test_ps_server_join_unblocks_on_shutdown(self):
        spec = ClusterSpec({"worker": ["w:1"], "ps": ["p:1"]})
        server = Server(spec, job_name="ps", task_index=0)
        assert not server.is_compute
        server.shutdown()
        server.join(timeout=5)  # must return immediately

    def test_single_worker_server_starts_without_distributed_init(self):
        spec = ClusterSpec({"worker": ["localhost:1"]})
        server = Server(spec, job_name="worker", task_index=0)
        assert server.is_compute
        assert server.target.startswith("jax://")


class TestMesh:
    def test_default_mesh_all_data(self, devices8):
        mesh = build_mesh(MeshConfig(), devices8)
        assert mesh.shape["data"] == 8
        assert all(mesh.shape[a] == 1 for a in mesh.shape if a != "data")

    def test_wildcard_and_fixed_axes(self, devices8):
        mesh = build_mesh(MeshConfig(data=-1, tensor=2, context=2), devices8)
        assert mesh.shape["data"] == 2
        assert mesh.shape["tensor"] == 2
        assert mesh.shape["context"] == 2

    def test_bad_factorization_raises(self, devices8):
        with pytest.raises(ValueError):
            build_mesh(MeshConfig(data=3, tensor=2), devices8)
        with pytest.raises(ValueError):
            build_mesh(MeshConfig(data=5), devices8)

    def test_single_axis_mesh(self, devices8):
        mesh = single_axis_mesh("tensor", devices8)
        assert mesh.shape["tensor"] == 8
        assert mesh.shape["data"] == 1

    def test_topology_detect(self):
        topo = Topology.detect()
        assert topo.num_devices == 8
        assert topo.platform == "cpu"


class TestHybridMesh:
    def test_single_slice_degrades_to_build_mesh(self):
        import jax

        from distributed_tensorflow_tpu.cluster import (
            MeshConfig,
            build_hybrid_mesh,
            build_mesh,
        )

        # CPU devices have no slice_index -> one slice -> plain build_mesh
        m = build_hybrid_mesh(MeshConfig(data=4, tensor=2))
        ref = build_mesh(MeshConfig(data=4, tensor=2))
        assert dict(m.shape) == dict(ref.shape)

    def test_indivisible_data_axis_raises(self):
        import jax
        import pytest

        from distributed_tensorflow_tpu.cluster import (
            MeshConfig,
            build_hybrid_mesh,
        )

        with pytest.raises(ValueError, match="divisible by the DCN"):
            build_hybrid_mesh(
                MeshConfig(data=4, tensor=2), dcn_data_parallelism=3,
            )
