"""Smoke tests for the serve entrypoints' driver contract: ONE parseable
JSON line from ``serve.py`` and from ``bench.py --mode=serve``.

Marked ``slow`` (excluded from tier-1, like test_bench_smoke.py) — each
subprocess compiles the tiny GPT-2 prefill + decode programs cold.  The
continuous-batching entrypoint smokes additionally carry ``serve_slow``
(they compile one slot-prefill program per distinct prompt length on top
of the decode step), so either marker alone keeps them out of tier-1.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable] + cmd,
        capture_output=True, text=True, timeout=1200, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert lines, f"no stdout; stderr: {proc.stderr[-2000:]}"
    return json.loads(lines[-1])  # the contract: last line is the JSON


@pytest.mark.slow
def test_serve_entrypoint_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--steps=16", "--prompt_len=8", "--max_new_tokens=4",
                "--max_batch_size=8"])
    for key in ("model", "requests", "completed", "tokens_per_sec",
                "p50_latency_ms", "p99_latency_ms", "avg_batch_occupancy",
                "batches", "checkpoint_step"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["completed"] == 16
    assert out["tokens_per_sec"] > 0
    assert out["p99_latency_ms"] >= out["p50_latency_ms"]
    assert out["checkpoint_step"] is None  # fresh-init smoke path


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_continuous_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--num_slots=8", "--steps=16",
                "--prompt_lens=6,8", "--max_new_tokens=6",
                "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    for key in ("tokens_per_sec", "slot_occupancy", "iterations",
                "admissions_per_iter", "retirements_per_iter",
                "ttft_p50_ms", "ttft_p99_ms", "tpot_mean_ms",
                "p50_latency_ms", "p99_latency_ms"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["completed"] == 16
    assert 0.0 < out["slot_occupancy"] <= 1.0
    assert out["ttft_p99_ms"] >= out["ttft_p50_ms"]


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_paged_int8_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--cache_mode=paged", "--block_size=8",
                "--kv_dtype=int8", "--num_slots=8", "--steps=16",
                "--prompt_lens=6,8", "--max_new_tokens=6",
                "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    assert out["cache_mode"] == "paged"
    assert out["kv_dtype"] == "int8"
    assert out["completed"] == 16
    assert out["kv_hbm_bytes"] > 0
    assert out["block_size"] == 8
    assert 0 < out["blocks_high_water"] <= out["blocks_total"]
    assert out["blocks_per_request_mean"] > 0


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_prefix_cache_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--cache_mode=paged", "--block_size=4",
                "--prefix_cache", "--shared_prefix_len=16",
                "--shared_prefix_groups=2", "--num_slots=8", "--steps=16",
                "--prompt_lens=6,8", "--max_new_tokens=6",
                "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    assert out["prefix_cache"] is True
    assert out["completed"] == 16
    assert out["prefix_hit_rate"] > 0
    assert out["prefill_tokens_skipped"] > 0
    assert out["prefix_cached_blocks"] >= 0
    assert len(out["tokens_checksum"]) == 16


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_chunked_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--prefill_budget=8", "--num_slots=8",
                "--steps=12", "--prompt_lens=6,8,40", "--max_new_tokens=6",
                "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    assert out["completed"] == 12
    assert out["prefill_budget"] == 8
    # Every 40-token prompt takes 5 chunks, so chunks > requests.
    assert out["prefill_chunks"] > 12
    assert out["tpot_p99_ms"] >= out["tpot_p50_ms"] >= 0
    assert len(out["tokens_checksum"]) == 16


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_megastep_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--megastep=4", "--num_slots=8",
                "--steps=12", "--prompt_lens=6,8", "--max_new_tokens=6",
                "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    assert out["completed"] == 12
    assert out["megastep"] == 4
    # One fused launch covers up to K tokens per slot: strictly fewer
    # launches than decoded tokens.
    assert 0 < out["megastep_launches"] < out["megastep_tokens"]
    assert out["tpot_p99_ms"] >= out["tpot_p50_ms"] >= 0
    assert len(out["tokens_checksum"]) == 16


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_spec_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous", "--spec_k=4", "--prompt_period=4",
                "--num_slots=8", "--steps=12", "--prompt_lens=8,12",
                "--max_new_tokens=8", "--min_new_tokens=4"])
    assert out["scheduler"] == "continuous"
    assert out["completed"] == 12
    assert out["spec_k"] == 4
    # The repetitive (motif-tiled) mix makes drafts land: accepted
    # tokens and launch amortization both show up in the counters.
    assert out["spec_launches"] > 0
    assert out["spec_acceptance_rate"] > 0
    assert 0 < out["megastep_launches"] < out["megastep_tokens"]
    assert len(out["tokens_checksum"]) == 16


@pytest.mark.slow
@pytest.mark.serve_slow
def test_serve_entrypoint_sampling_mix_prints_one_json_line():
    out = _run([os.path.join(REPO, "serve.py"), "--model=gpt2",
                "--continuous",
                "--sampling_mix=greedy:0.5,t0.8k40:0.3,t1.0p0.9:0.2",
                "--num_slots=8", "--steps=16", "--prompt_lens=6,8",
                "--max_new_tokens=6", "--min_new_tokens=2"])
    assert out["scheduler"] == "continuous"
    assert out["completed"] == 16
    assert out["sampling_mix"] == "greedy:0.5,t0.8k40:0.3,t1.0p0.9:0.2"
    assert out["sampling_configs"] == 3
    # The tentpole claim at the entrypoint: a heterogeneous mix shares
    # ONE compiled program set — nothing compiles after warmup, and the
    # cache holds the per-family programs, not one per config.
    assert out["compile_post_warmup"] == 0
    assert 0 < out["programs_cached"] <= 4
    assert out["compile_total"] == out["programs_cached"]


@pytest.mark.slow
@pytest.mark.serve_slow
def test_bench_serve_mode_prints_one_json_line():
    out = _run([os.path.join(REPO, "bench.py"), "--mode=serve",
                "--serve_requests=16"])
    for key in ("metric", "value", "unit", "vs_baseline",
                "p50_latency_ms", "p99_latency_ms",
                "ttft_p50_ms", "tpot_mean_ms", "slot_occupancy",
                "fixed_tokens_per_sec", "continuous_speedup",
                "paged_tokens_per_sec", "paged_speedup",
                "paged_int8_tokens_per_sec", "kv_hbm_bytes",
                "kv_hbm_ratio_paged", "kv_hbm_ratio_paged_int8",
                "block_size", "num_blocks", "block_utilization",
                "queue_wait_p50_ms", "queue_wait_p99_ms", "trace_events"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["unit"] == "tokens/sec"
    assert out["value"] > 0
    assert out["fixed_tokens_per_sec"] > 0
    assert out["paged_tokens_per_sec"] > 0
    assert "serve_tokens_per_sec" in out["metric"]
    # the trace-export smoke: the bench runs with the flight recorder on,
    # so the continuous runs must have recorded per-request spans
    assert out["trace_events"] > 0
    assert out["queue_wait_p99_ms"] >= out["queue_wait_p50_ms"] >= 0
    # the memory claim: paged <= 0.5x dense cache bytes, int8 <= 0.25x
    assert out["kv_hbm_bytes"]["paged"] < out["kv_hbm_bytes"]["dense"]
    assert out["kv_hbm_ratio_paged"] <= 0.5
    assert out["kv_hbm_ratio_paged_int8"] <= 0.25
    # the prefix-caching claim: shared-prefix traffic hits the cache and
    # the warm run's greedy tokens are bit-identical to the cold run's
    for key in ("prefix_hit_rate", "prefill_tokens_skipped",
                "ttft_speedup_prefix", "prefix_parity"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["prefix_hit_rate"] > 0
    assert out["prefill_tokens_skipped"] > 0
    assert out["prefix_parity"] is True
    # the chunked-prefill claim: the skewed whale mix's inter-token gap
    # p99 improves (or at worst matches), the whale actually chunked, and
    # greedy output is bit-identical budget on vs off — alone and
    # composed with the prefix cache and the per-shard pool
    for key in ("tpot_p99_unchunked", "tpot_p99_chunked",
                "unchunked_tokens_per_sec", "chunked_tokens_per_sec",
                "chunked_prefill_budget"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["tpot_p99_speedup_chunked"] >= 1.0
    assert out["chunked_prefill_chunks"] > 0
    assert out["chunked_parity"] is True
    assert out["chunked_prefix_parity"] is True
    assert out["chunked_prefix_skip_parity"] is True
    assert out["chunked_pershard_parity"] is True
    # the megastep claim: K fused decode steps per dispatch beat (or at
    # worst match) the per-token launch on the same traffic, at the same
    # greedy checksum
    for key in ("megastep", "megastep_tokens_per_sec",
                "megastep_base_tokens_per_sec", "megastep_launches",
                "megastep_base_launches"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["megastep"] == 8
    assert out["megastep_parity"] is True
    assert out["megastep_speedup"] >= 1.0
    assert out["megastep_launches"] < out["megastep_base_launches"]
    # the speculative-decoding claim: on the repetitive mix the drafter
    # lands, the verifier emits more than one token per launch
    # (steps-per-token speedup > 1), and greedy output stays
    # bit-identical spec on vs off — alone and composed with chunked
    # prefill, the megastep, and the prefix cache
    for key in ("spec_k", "spec_steps_per_token",
                "spec_base_steps_per_token", "spec_launches",
                "spec_drafted", "spec_accepted"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["spec_k"] == 4
    assert out["spec_parity"] is True
    assert out["spec_acceptance_rate"] > 0
    assert out["spec_speedup"] >= 1.0
    assert out["spec_steps_per_token"] < out["spec_base_steps_per_token"]
    assert out["spec_chunked_parity"] is True
    assert out["spec_megastep_parity"] is True
    assert out["spec_prefix_parity"] is True
    # the vectorized-sampling claim: a heterogeneous per-request mix
    # runs on ONE compiled program set (zero post-warmup compiles),
    # while the scalar fixed-batch path pays one program set per config
    for key in ("sampling_mix", "sampling_configs",
                "sampling_tokens_per_sec", "sampling_programs_cached",
                "sampling_compile_post_warmup",
                "sampling_scalar_program_sets"):
        assert key in out, f"missing {key!r} in {out}"
    assert out["sampling_configs"] == 3
    assert out["sampling_compile_post_warmup"] == 0
    assert out["sampling_scalar_program_sets"] == 3
    assert out["sampling_tokens_per_sec"] > 0
