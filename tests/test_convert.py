"""TFRecord → RecordFile converter tests (real-dataset ingestion for
--data_dir; VERDICT missing #7).  Writes genuine TFRecord files with
TensorFlow's writer, converts them, and trains through the native loader.
"""

import os
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_tpu.data.convert import (  # noqa: E402
    convert_tfrecords,
    iter_tfrecord,
    parse_example,
)
from distributed_tensorflow_tpu.data.records import record_path  # noqa: E402
from distributed_tensorflow_tpu.models import get_workload  # noqa: E402


def _write_tfrecord(path, examples):
    with tf.io.TFRecordWriter(str(path)) as w:
        for ex in examples:
            feats = {}
            for name, val in ex.items():
                val = np.asarray(val)
                if val.dtype.kind == "f":
                    feats[name] = tf.train.Feature(
                        float_list=tf.train.FloatList(value=val.ravel())
                    )
                else:
                    feats[name] = tf.train.Feature(
                        int64_list=tf.train.Int64List(value=val.ravel())
                    )
            w.write(tf.train.Example(
                features=tf.train.Features(feature=feats)
            ).SerializeToString())


def test_iter_and_parse_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    examples = [
        {"x": rng.randn(4).astype(np.float32), "y": np.int64(i)}
        for i in range(10)
    ]
    p = tmp_path / "a.tfrecord"
    _write_tfrecord(p, examples)
    got = [parse_example(buf) for buf in iter_tfrecord(str(p))]
    assert len(got) == 10
    for ex, g in zip(examples, got):
        np.testing.assert_allclose(g["x"], ex["x"], rtol=1e-6)
        assert g["y"][0] == ex["y"]


def test_truncated_trailing_crc_raises(tmp_path):
    """A file cut inside the final 4-byte payload CRC must raise, not be
    accepted silently (ADVICE r2)."""
    p = tmp_path / "t.tfrecord"
    _write_tfrecord(p, [{"x": np.float32([1, 2]), "y": np.int64(0)}])
    raw = p.read_bytes()
    (tmp_path / "cut.tfrecord").write_bytes(raw[:-2])  # inside the CRC
    with pytest.raises(ValueError, match="truncated TFRecord payload CRC"):
        list(iter_tfrecord(str(tmp_path / "cut.tfrecord")))


def test_verify_catches_corruption_and_passes_clean(tmp_path):
    p = tmp_path / "v.tfrecord"
    examples = [{"x": np.float32([i, i + 1]), "y": np.int64(i)}
                for i in range(3)]
    _write_tfrecord(p, examples)
    # clean file verifies
    assert len(list(iter_tfrecord(str(p), verify=True))) == 3
    # flip one payload byte: well-framed but corrupt -> verify raises,
    # non-verify (framing-only) still yields all records
    raw = bytearray(p.read_bytes())
    raw[15] ^= 0xFF  # first payload byte (after 12-byte header)
    bad = tmp_path / "bad.tfrecord"
    bad.write_bytes(bytes(raw))
    assert len(list(iter_tfrecord(str(bad)))) == 3
    with pytest.raises(ValueError, match="CRC mismatch"):
        list(iter_tfrecord(str(bad), verify=True))


def test_convert_then_train_mnist(tmp_path):
    """Full ingestion path: TFRecord shards -> RecordFile -> native loader
    -> training (loss finite)."""
    from distributed_tensorflow_tpu.train_lib import TrainArgs, run

    wl = get_workload("mnist", batch_size=16)
    rng = np.random.RandomState(1)
    n = 128
    shard_a = [
        {"image": rng.randn(28, 28, 1).astype(np.float32),
         "label": np.int64(rng.randint(10))}
        for _ in range(n // 2)
    ]
    shard_b = [
        {"image": rng.randn(28, 28, 1).astype(np.float32),
         "label": np.int64(rng.randint(10))}
        for _ in range(n // 2)
    ]
    _write_tfrecord(tmp_path / "train-00000", shard_a)
    _write_tfrecord(tmp_path / "train-00001", shard_b)

    def transform(ex):
        return {
            "image": ex["image"].reshape(28, 28, 1).astype(np.float32),
            "label": ex["label"].astype(np.int32)[0],
        }

    out = record_path(str(tmp_path / "staged"), "mnist")
    wrote = convert_tfrecords(
        [str(tmp_path / "train-00000"), str(tmp_path / "train-00001")],
        out, workload=wl, transform=transform,
    )
    assert wrote == n

    result = run(TrainArgs(
        model="mnist", steps=6, batch_size=16, log_every=3,
        data_dir=str(tmp_path / "staged"),
    ))
    assert result["final_step"] == 6
    assert np.isfinite(result["loss"])


def test_convert_applies_to_record_staging(tmp_path):
    """Workload.to_record (uint8 image staging) applies during conversion:
    resnet records land quantized on disk."""
    from distributed_tensorflow_tpu.data.records import record_schema

    wl = get_workload("resnet50", batch_size=8, num_classes=4,
                      image_size=8, stage_sizes=(1, 1, 1, 1))
    rng = np.random.RandomState(2)
    exs = [
        {"image": rng.randn(8, 8, 3).astype(np.float32),
         "label": np.int64(rng.randint(4))}
        for _ in range(32)
    ]
    p = tmp_path / "rn.tfrecord"
    _write_tfrecord(p, exs)

    def transform(ex):
        return {
            "image": ex["image"].reshape(8, 8, 3).astype(np.float32),
            "label": ex["label"].astype(np.int32)[0],
        }

    out = record_path(str(tmp_path / "staged"), "resnet50")
    wrote = convert_tfrecords([str(p)], out, workload=wl, transform=transform)
    assert wrote == 32
    schema = record_schema(wl)
    import os

    assert os.path.getsize(out) == schema.file_size(32)
    # image field staged as uint8 (quarter the f32 size)
    dtypes = {n: d for n, _, d in schema.fields}
    assert dtypes["image"] == np.uint8


def test_limit_and_missing_field_error(tmp_path):
    wl = get_workload("mnist", batch_size=8)
    rng = np.random.RandomState(3)
    exs = [
        {"image": rng.randn(28, 28, 1).astype(np.float32),
         "label": np.int64(1)}
        for _ in range(20)
    ]
    p = tmp_path / "m.tfrecord"
    _write_tfrecord(p, exs)

    def transform(ex):
        return {
            "image": ex["image"].reshape(28, 28, 1).astype(np.float32),
            "label": ex["label"].astype(np.int32)[0],
        }

    out = record_path(str(tmp_path / "staged"), "mnist")
    wrote = convert_tfrecords([str(p)], out, workload=wl,
                              transform=transform, limit=12)
    assert wrote == 12

    # an example stream missing a schema field is a hard error
    p2 = tmp_path / "nolabel.tfrecord"
    _write_tfrecord(p2, [{"image": np.zeros(784, np.float32)}])
    with pytest.raises(ValueError, match="lacks schema fields"):
        convert_tfrecords([str(p2)], str(tmp_path / "bad.rec"), workload=wl)


def test_convert_to_fileset_then_train_file_sharded(tmp_path):
    """TFRecords -> {name}-NNNNN-of-MMMMM.rec fileset (num_output_files) ->
    FILE-policy training (VERDICT r3 #4)."""
    from distributed_tensorflow_tpu.data.records import record_paths
    from distributed_tensorflow_tpu.train_lib import TrainArgs, run

    wl = get_workload("mnist", batch_size=16)
    rng = np.random.RandomState(2)
    n = 96
    examples = [
        {"image": rng.randn(28, 28, 1).astype(np.float32),
         "label": np.int64(rng.randint(10))}
        for _ in range(n)
    ]
    _write_tfrecord(tmp_path / "train-00000", examples)

    def transform(ex):
        return {
            "image": ex["image"].reshape(28, 28, 1).astype(np.float32),
            "label": ex["label"].astype(np.int32)[0],
        }

    out = record_path(str(tmp_path / "staged"), "mnist")
    wrote = convert_tfrecords(
        [str(tmp_path / "train-00000")], out, workload=wl,
        transform=transform, num_output_files=4,
    )
    assert wrote == n
    paths = record_paths(str(tmp_path / "staged"), "mnist")
    assert len(paths) == 4
    # round-robin split: 24 records per member
    from distributed_tensorflow_tpu.data.records import record_schema
    schema = record_schema(wl)
    for p in paths:
        payload = os.path.getsize(p) - 16
        assert payload // schema.record_bytes == n // 4

    result = run(TrainArgs(
        model="mnist", steps=4, batch_size=16, log_every=2,
        data_dir=str(tmp_path / "staged"), auto_shard_policy="file",
    ))
    assert result["final_step"] == 4
    assert np.isfinite(result["loss"])
