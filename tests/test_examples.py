"""End-to-end tests for the reference-idiom example launchers.

Closes VERDICT weak #6: ``compat.v1``'s "train.py runs unchanged" claim is
demonstrated by *executing* a TF1-style PS launcher script (ClusterSpec +
Server + replica_device_setter + MonitoredTrainingSession +
SyncReplicasOptimizer), not just checking call shapes.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from tests.helpers import free_ports

REPO = os.path.dirname(os.path.dirname(__file__))
LAUNCHER = os.path.join(REPO, "examples", "tf1_ps_launcher.py")



def _env():
    return dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PALLAS_AXON_POOL_IPS="",
    )


def test_tf1_ps_launcher_single_process(tmp_path):
    """The reference's local-run mode: one process, trains BERT-tiny end to
    end through every TF1 shim, checkpoints, and reports a finite loss."""
    ckpt = tmp_path / "ckpt"
    out = subprocess.run(
        [
            sys.executable, LAUNCHER,
            "--train_steps", "8", "--batch_size", "8", "--seq_len", "32",
            "--sync_replicas", "2", "--log_every", "2",
            "--checkpoint_dir", str(ckpt),
        ],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "TF1_PS_LAUNCHER_DONE" in out.stdout, out.stdout[-2000:]
    line = [l for l in out.stdout.splitlines() if "TF1_PS_LAUNCHER_DONE" in l][0]
    loss = float(line.split("loss=")[1])
    assert loss == loss and loss > 0  # finite, nonzero
    # chief-only MonitoredTrainingSession checkpointing really saved
    assert any(ckpt.iterdir()), "no checkpoint written"


def test_tf1_ps_launcher_ps_and_worker(tmp_path):
    """Reference cluster mode: a real ps process parks in Server.join() while
    the worker trains; worker completion terminates the ps (launcher
    contract, SURVEY.md §4.2)."""
    ps_port, w_port = free_ports(2)
    common = [
        "--ps_hosts", f"localhost:{ps_port}",
        "--worker_hosts", f"localhost:{w_port}",
        "--train_steps", "4", "--batch_size", "8", "--seq_len", "32",
        "--log_every", "2",
    ]
    ps = subprocess.Popen(
        [sys.executable, LAUNCHER, "--job_name", "ps", "--task_index", "0",
         *common],
        env=_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        worker = subprocess.run(
            [sys.executable, LAUNCHER, "--job_name", "worker",
             "--task_index", "0", *common],
            env=_env(), cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert worker.returncode == 0, worker.stderr[-4000:]
        assert "TF1_PS_LAUNCHER_DONE" in worker.stdout, worker.stdout[-2000:]
        # the ps task is still parked in join() — the TF1 contract
        assert ps.poll() is None, "ps task exited instead of parking in join()"
    finally:
        ps.terminate()
        ps.wait(timeout=30)


def test_migrate_from_tf_example(tmp_path):
    """The migration showcase: real TF checkpoint -> pure-python bundle
    reader -> params tree -> training fed by a real tf.data pipeline."""
    pytest.importorskip("tensorflow")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "migrate_from_tf.py")],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "MIGRATE_FROM_TF_DONE" in out.stdout, out.stdout[-2000:]
    line = [l for l in out.stdout.splitlines()
            if "MIGRATE_FROM_TF_DONE" in l][0]
    assert "step=10" in line
