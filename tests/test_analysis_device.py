"""dttlint v3 device-boundary rules: each seeded fixture in
``tests/analysis_fixtures/`` is detected at its exact ``path:line``
(markers are rule-specific, ``# SEED: <rule-id>``), each clean twin
stays silent, the real tree is clean end to end, and re-introducing a
donated-cache read in a scratch copy of ``serve/engine.py`` makes
``use-after-donate`` fire — the rule guards the engine's documented
donated-cache chaining idiom, not just the fixture."""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from distributed_tensorflow_tpu.analysis import load_modules
from distributed_tensorflow_tpu.analysis.__main__ import default_targets
from distributed_tensorflow_tpu.analysis.concurrency import _FACTS_CACHE
from distributed_tensorflow_tpu.analysis.core import collect_files
from distributed_tensorflow_tpu.analysis.device import (
    _DEVICE_CACHE,
    DonationDisciplineRule,
    HostSyncRule,
    UseAfterDonateRule,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analysis_fixtures"


def seeded_lines(path: Path, rule_id: str):
    """Lines carrying this rule's ``# SEED: <rule-id>`` marker."""
    marker = f"# SEED: {rule_id}"
    return [i for i, line in enumerate(path.read_text().splitlines(), 1)
            if marker in line]


def run_rule_on(rule, path: Path, root: Path = REPO_ROOT):
    # Both fact layers cache per module list; stay hermetic.
    _FACTS_CACHE.clear()
    _DEVICE_CACHE.clear()
    modules, errors = load_modules([path], root)
    assert not errors, errors
    return rule.run(modules)


CASES = [
    ("donate", UseAfterDonateRule, "use-after-donate"),
    ("donate", DonationDisciplineRule, "donation-discipline"),
    ("hostsync", HostSyncRule, "host-sync"),
    ("async", UseAfterDonateRule, "use-after-donate"),
    ("async", HostSyncRule, "host-sync"),
    ("asyncring", UseAfterDonateRule, "use-after-donate"),
    ("asyncring", HostSyncRule, "host-sync"),
    ("gateway", HostSyncRule, "host-sync"),
    ("tiering", HostSyncRule, "host-sync"),
    ("lifecycle", HostSyncRule, "host-sync"),
]


class TestSeededFixtures:
    """Each bad fixture fires at exactly its SEED-marked lines; each
    clean twin produces zero findings from the same rule."""

    @pytest.mark.parametrize("stem,rule_cls,rule_id", CASES)
    def test_bad_fixture_fires_at_seeded_lines(self, stem, rule_cls,
                                               rule_id):
        path = FIXTURES / f"{stem}_bad.py"
        expected = seeded_lines(path, rule_id)
        assert expected, f"{path} has no SEED markers for {rule_id}"
        findings = [f for f in run_rule_on(rule_cls(), path)
                    if f.rule == rule_id]
        got = sorted(f.line for f in findings)
        assert got == expected, [f.format() for f in findings]

    @pytest.mark.parametrize("stem,rule_cls,rule_id", CASES)
    def test_clean_twin_is_silent(self, stem, rule_cls, rule_id):
        path = FIXTURES / f"{stem}_clean.py"
        findings = [f for f in run_rule_on(rule_cls(), path)
                    if f.rule == rule_id]
        assert findings == [], [f.format() for f in findings]

    def test_alias_through_self_attr_is_named(self):
        """``refill`` donates ``self._cache`` and re-reads it: the
        finding names the attribute, proving taint follows attribute
        aliases, not just local names."""
        findings = run_rule_on(UseAfterDonateRule(),
                               FIXTURES / "donate_bad.py")
        attr_hits = [f for f in findings if "self._cache" in f.message]
        assert attr_hits, [f.format() for f in findings]

    def test_hot_helper_via_call_graph(self):
        """``_flush_stats`` has no loop of its own — it is hot only
        because ``decode``'s launch loop calls it."""
        findings = run_rule_on(HostSyncRule(),
                               FIXTURES / "hostsync_bad.py")
        helper_hits = [f for f in findings if f.symbol.endswith(
            "_flush_stats")]
        assert helper_hits, [f.format() for f in findings]


class TestRealTreeClean:
    """The three device rules hold over the shipped tree with ZERO
    baseline entries — every real finding was fixed, not suppressed."""

    def test_device_rules_clean_on_default_targets(self):
        _FACTS_CACHE.clear()
        _DEVICE_CACHE.clear()
        files = collect_files(default_targets(REPO_ROOT), REPO_ROOT)
        modules, errors = load_modules(files, REPO_ROOT)
        assert not errors, errors
        for rule_cls in (UseAfterDonateRule, HostSyncRule,
                         DonationDisciplineRule):
            findings = rule_cls().run(modules)
            assert findings == [], [f.format() for f in findings]


class TestDonatedCacheInvariant:
    """Re-introducing the hand-documented hazard — reading ``cache``
    after the donated prefill launch in ``serve/engine.py`` — is caught
    in a scratch copy of the tree."""

    def test_cache_read_after_donated_launch_trips_rule(self, tmp_path):
        scratch = tmp_path / "scratch"
        shutil.copytree(
            REPO_ROOT / "distributed_tensorflow_tpu",
            scratch / "distributed_tensorflow_tpu",
            ignore=shutil.ignore_patterns("__pycache__"))
        engine = scratch / "distributed_tensorflow_tpu" / "serve" / "engine.py"
        src = engine.read_text()
        anchor = 'self._obs["prefill"].observe(time.perf_counter() - t0)'
        assert anchor in src
        engine.write_text(
            src.replace(anchor, anchor + "\n        _stale = cache", 1))

        _FACTS_CACHE.clear()
        _DEVICE_CACHE.clear()
        files = collect_files([scratch / "distributed_tensorflow_tpu"],
                              scratch)
        modules, errors = load_modules(files, scratch)
        assert not errors, errors
        findings = UseAfterDonateRule().run(modules)
        engine_hits = [f for f in findings
                       if f.path == "distributed_tensorflow_tpu/serve/engine.py"]
        assert engine_hits, "donated-cache read in engine.py went undetected"
        _FACTS_CACHE.clear()
        _DEVICE_CACHE.clear()


class TestCli:
    """The device rules ride the existing runner surface:
    --changed-only picks them up from a stdin file list."""

    def _run(self, *argv, stdin=None, cwd=REPO_ROOT):
        return subprocess.run(
            [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
             *argv],
            input=stdin, capture_output=True, text=True, cwd=cwd,
            timeout=300)

    def test_changed_only_flags_bad_fixture(self):
        proc = self._run(
            "--changed-only", "--no-baseline",
            stdin="tests/analysis_fixtures/donate_bad.py\n")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "use-after-donate" in proc.stdout
        assert "donation-discipline" in proc.stdout

    def test_changed_only_clean_fixture_passes(self):
        proc = self._run(
            "--changed-only", "--no-baseline",
            stdin="tests/analysis_fixtures/donate_clean.py\n")
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestFileWalk:
    """collect_files never descends into cache directories — a stale
    ``__pycache__``/``.pytest_cache`` artifact must not become a
    finding."""

    def test_cache_dirs_are_skipped(self, tmp_path):
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "real.py").write_text("x = 1\n")
        for cache in ("__pycache__", ".pytest_cache"):
            d = tmp_path / "pkg" / cache
            d.mkdir()
            (d / "planted.py").write_text("import os, sys  # junk\n")
        files = collect_files([tmp_path], tmp_path)
        names = sorted(p.name for p in files)
        assert names == ["real.py"], names

    def test_default_targets_exclude_caches(self):
        files = collect_files(default_targets(REPO_ROOT), REPO_ROOT)
        offenders = [p for p in files
                     if "__pycache__" in p.parts
                     or ".pytest_cache" in p.parts]
        assert offenders == []
