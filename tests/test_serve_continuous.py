"""Continuous-batching tests: slot-mode cache semantics in the model, the
engine's slot APIs (prefill_into_slots / decode_slots over one resident
cache), and the ContinuousScheduler's contract — token-for-token parity
with the fixed-batch path on mixed traffic, slot reuse without stale K/V,
overload rejection, sampling, and the iteration-level batcher front.

Parity runs on BOTH acceptance meshes: the pure data-parallel mesh and
data=4 x tensor=2 (params sharded by gpt2_rules, resident cache by
gpt2_cache_rules).  Greedy decode is deterministic on CPU, so parity is
exact array equality, not tolerance.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.serve import (
    ContinuousScheduler,
    DynamicBatcher,
    ServeEngine,
    ServeOverloadedError,
)


def _mixed_requests(vocab, n=20, seed=1):
    """Mixed prompt lengths AND mixed horizons — the traffic continuous
    batching exists for."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        length = (4, 6, 9)[i % 3]
        horizon = (2, 5, 3, 7)[i % 4]
        reqs.append((rng.integers(0, vocab, size=(length,), dtype=np.int32),
                     horizon))
    return reqs


def _fixed_reference(engine, prompt, max_new_tokens):
    """The fixed-batch answer for one prompt: a full padded-batch greedy
    generate, row 0.  Greedy decode is row-independent, so this is the
    token-for-token target for the continuous path."""
    rows = engine.bucket_rows(1)
    out = engine.generate(np.repeat(prompt[None, :], rows, axis=0),
                          max_new_tokens)
    return out[0]


# ---------------------------------------------------------------------------
# Model layer: slot_ids threading through the decode cache
# ---------------------------------------------------------------------------

def _tiny_gpt2(**kw):
    from distributed_tensorflow_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config.tiny(dtype=jnp.float32, **kw)
    return GPT2(cfg), cfg


class TestSlotModeCache:
    def test_slot_cache_index_is_per_slot_vector(self):
        model, _ = _tiny_gpt2()
        num_slots, T = 4, 8
        vs = jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((num_slots, T), jnp.int32),
            decode=True, slot_ids=jnp.arange(num_slots)))
        flat = {"/".join(str(k.key) for k in path): leaf
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    vs["cache"])[0]}
        idx = next(v for k, v in flat.items() if "cache_index" in k)
        # scan stacks the per-layer caches: (L, num_slots) not scalar (L,)
        assert idx.shape[-1] == num_slots

    def test_slot_subset_prefill_matches_full_forward(self):
        """Prefill into a SUBSET of slots at arbitrary ids; logits must
        match the plain forward, and untouched slots' index rows stay 0."""
        model, cfg = _tiny_gpt2()
        num_slots, T = 8, 6
        tokens = np.asarray(jax.random.randint(
            jax.random.key(1), (2, T), 0, cfg.vocab_size))
        params = model.init(jax.random.key(0), tokens)["params"]
        full = model.apply({"params": params}, jnp.asarray(tokens))

        shapes = jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((num_slots, T), jnp.int32),
            decode=True, slot_ids=jnp.arange(num_slots)))["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        slot_ids = jnp.asarray([5, 2])  # non-contiguous, out of order
        logits, vs = model.apply(
            {"params": params, "cache": cache}, jnp.asarray(tokens),
            decode=True, slot_ids=slot_ids, mutable=["cache"])
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                                   rtol=1e-4, atol=1e-4)
        flat = {"/".join(str(k.key) for k in path): leaf
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    vs["cache"])[0]}
        idx = np.asarray(next(v for k, v in flat.items()
                              if "cache_index" in k))
        assert (idx[:, [5, 2]] == T).all()
        untouched = [s for s in range(num_slots) if s not in (5, 2)]
        assert (idx[:, untouched] == 0).all()

    def test_slot_ids_without_decode_rejected(self):
        model, _ = _tiny_gpt2()
        with pytest.raises(ValueError, match="slot_ids"):
            model.init(jax.random.key(0), jnp.zeros((2, 4), jnp.int32),
                       slot_ids=jnp.arange(2))


# ---------------------------------------------------------------------------
# Engine layer: resident slot cache APIs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


class TestEngineSlotAPIs:
    def test_init_slot_cache_validates_geometry(self, gpt2_engine):
        with pytest.raises(ValueError, match="multiple"):
            gpt2_engine.init_slot_cache(3, 16)  # dp=8 on the 8-way mesh
        n_pos = gpt2_engine.module.cfg.n_positions
        with pytest.raises(ValueError, match="n_positions"):
            gpt2_engine.init_slot_cache(8, n_pos + 1)

    def test_prefill_then_decode_matches_generate(self, gpt2_engine):
        """Drive the slot APIs by hand — per-slot prefill at staggered
        times, then shared (num_slots, 1) steps — and compare each slot's
        stream to the fixed-batch generate, token for token."""
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, vocab, size=(n,), dtype=np.int32)
                   for n in (5, 7, 5)]
        cache = gpt2_engine.init_slot_cache(8, 24)
        last = np.zeros((8, 1), np.int32)
        streams = {s: [] for s in (6, 1, 3)}
        for prompt, slot in zip(prompts, (6, 1, 3)):
            tok, cache = gpt2_engine.prefill_into_slots(
                cache, prompt[None, :], [slot])
            streams[slot].append(int(np.asarray(jax.device_get(tok))[0]))
            last[slot, 0] = streams[slot][-1]
        active = np.zeros((8,), bool)
        active[[6, 1, 3]] = True
        for _ in range(4):
            tok, cache = gpt2_engine.decode_slots(cache, last, active)
            toks = np.asarray(jax.device_get(tok))
            for slot in (6, 1, 3):
                streams[slot].append(int(toks[slot]))
                last[slot, 0] = toks[slot]
        for prompt, slot in zip(prompts, (6, 1, 3)):
            ref = _fixed_reference(gpt2_engine, prompt, 5)
            np.testing.assert_array_equal(np.asarray(streams[slot]), ref)

    def test_inactive_slots_do_not_advance(self, gpt2_engine):
        """The active-mask contract: a decode step must not move an
        inactive slot's cache_index/position rows."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.arange(4, dtype=np.int32) % vocab
        cache = gpt2_engine.init_slot_cache(8, 16)
        _, cache = gpt2_engine.prefill_into_slots(cache, prompt[None, :], [0])
        _, cache = gpt2_engine.prefill_into_slots(cache, prompt[None, :], [5])

        def index_rows(c):
            flat = {"/".join(str(k.key) for k in path): leaf
                    for path, leaf in jax.tree_util.tree_flatten_with_path(
                        c)[0]}
            return np.asarray(next(v for k, v in flat.items()
                                   if "cache_index" in k))

        before = index_rows(cache)
        active = np.zeros((8,), bool)
        active[0] = True
        _, cache = gpt2_engine.decode_slots(
            cache, np.zeros((8, 1), np.int32), active)
        after = index_rows(cache)
        assert (after[:, 0] == before[:, 0] + 1).all()   # active advanced
        assert (after[:, 5] == before[:, 5]).all()       # inactive frozen
        assert (after[:, 1] == 0).all()                  # empty untouched


# ---------------------------------------------------------------------------
# ContinuousScheduler: parity, reuse, overload, sampling
# ---------------------------------------------------------------------------

class TestContinuousScheduler:
    def test_mixed_traffic_parity_with_fixed_batch(self, gpt2_engine):
        """THE acceptance property: greedy continuous decode of mixed-length
        mixed-horizon requests is token-for-token identical to the
        fixed-batch path — more requests than slots, so every slot is
        reused at least once (stale-K/V hygiene is load-bearing here)."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=20)
        with ContinuousScheduler(gpt2_engine, num_slots=8,
                                 max_total_len=32) as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
            s = sched.stats()
        assert s["completed"] == float(len(reqs))
        assert s["retired"] == float(len(reqs))
        assert s["iterations"] > 0
        assert 0.0 < s["slot_occupancy"] <= 1.0
        assert s["ttft_p50_ms"] > 0.0
        for (prompt, horizon), out in zip(reqs, outs):
            assert out.shape == (horizon,) and out.dtype == np.int32
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))

    def test_parity_under_tensor_parallel_mesh(self, mesh_2d):
        """Same parity on the data=4 x tensor=2 mesh (the --tensor=2
        acceptance configuration): slot rows shard over data, heads over
        tensor."""
        with ServeEngine("gpt2", mesh=mesh_2d, preset="tiny") as eng:
            vocab = eng.module.cfg.vocab_size
            reqs = _mixed_requests(vocab, n=10, seed=7)
            with ContinuousScheduler(eng, num_slots=4,
                                     max_total_len=32) as sched:
                futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
                outs = [f.result(timeout=300) for f in futs]
            for (prompt, horizon), out in zip(reqs, outs):
                np.testing.assert_array_equal(
                    out, _fixed_reference(eng, prompt, horizon))

    def test_eos_retires_slot_early(self, gpt2_engine):
        """A request whose greedy stream hits its eos token retires at the
        eos, shorter than its horizon."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.arange(6, dtype=np.int32) % vocab
        ref = _fixed_reference(gpt2_engine, prompt, 8)
        eos = int(ref[3])  # force an eos hit mid-stream
        cut = int(np.flatnonzero(ref == eos)[0]) + 1  # first occurrence
        with ContinuousScheduler(gpt2_engine, num_slots=8,
                                 max_total_len=32) as sched:
            out = sched.submit(prompt, max_new_tokens=8,
                               eos_token=eos).result(timeout=300)
        assert len(out) == cut < 8
        assert out[-1] == eos
        np.testing.assert_array_equal(out, ref[:cut])

    def test_overload_rejection_and_close(self, gpt2_engine):
        """Unstarted loop -> the admission queue fills to its bound and
        rejects; close() fails the stranded futures."""
        prompt = np.zeros((4,), np.int32)
        cold = ContinuousScheduler(gpt2_engine, num_slots=8,
                                   max_total_len=16, max_queue_size=3,
                                   start=False)
        futs = [cold.submit(prompt, max_new_tokens=2) for _ in range(3)]
        with pytest.raises(ServeOverloadedError):
            cold.submit(prompt, max_new_tokens=2)
        assert cold.stats()["rejected"] == 1.0
        cold.close(timeout=0.1)
        for f in futs:
            with pytest.raises(RuntimeError):
                f.result(timeout=5)
        with pytest.raises(RuntimeError):
            cold.submit(prompt, max_new_tokens=2)

    def test_submit_validates_total_length(self, gpt2_engine):
        with ContinuousScheduler(gpt2_engine, num_slots=8,
                                 max_total_len=16) as sched:
            with pytest.raises(ValueError, match="max_total_len"):
                sched.submit(np.zeros((12,), np.int32), max_new_tokens=8)
            with pytest.raises(ValueError, match="max_new_tokens"):
                sched.submit(np.zeros((4,), np.int32), max_new_tokens=0)

    def test_rejects_model_without_decode_cache(self, mesh_dp):
        with ServeEngine("mnist", mesh=mesh_dp, batch_size=32) as eng:
            with pytest.raises(ValueError, match="decode"):
                ContinuousScheduler(eng, start=False)


class TestSampling:
    def test_top_k_one_equals_greedy(self, gpt2_engine):
        """temperature > 0 with top_k=1 can only pick the argmax — the
        sampling path must reproduce the greedy stream exactly."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=6, seed=11)
        with ContinuousScheduler(gpt2_engine, num_slots=8, max_total_len=32,
                                 temperature=0.7, top_k=1) as sched:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))

    def test_sampled_generate_valid_and_seeded(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        prompts = np.asarray(jax.random.randint(
            jax.random.key(6), (8, 5), 0, vocab))
        rng = jax.random.key(9)
        a = gpt2_engine.generate(prompts, 6, temperature=0.9, top_k=8,
                                 rng=rng)
        b = gpt2_engine.generate(prompts, 6, temperature=0.9, top_k=8,
                                 rng=rng)
        assert a.shape == (8, 6)
        assert (a >= 0).all() and (a < vocab).all()
        np.testing.assert_array_equal(a, b)  # same key -> same stream


# ---------------------------------------------------------------------------
# DynamicBatcher iteration-level front
# ---------------------------------------------------------------------------

class TestIterationLevelBatcher:
    def test_streams_to_scheduler_with_same_surface(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=6, seed=5)
        sched = ContinuousScheduler(gpt2_engine, num_slots=8,
                                    max_total_len=32)
        with DynamicBatcher(iteration_level=True, scheduler=sched) as b:
            futs = [b.submit((p, m)) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
            s = b.stats()
        assert "slot_occupancy" in s  # the scheduler's snapshot
        assert s["completed"] == float(len(reqs))
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))

    def test_iteration_level_requires_scheduler(self):
        with pytest.raises(ValueError, match="scheduler"):
            DynamicBatcher(iteration_level=True)
        with pytest.raises(ValueError, match="run_batch"):
            DynamicBatcher(lambda p: p, iteration_level=True,
                           scheduler=object())

    def test_closed_batcher_rejects_submit(self, gpt2_engine):
        sched = ContinuousScheduler(gpt2_engine, num_slots=8,
                                    max_total_len=16)
        b = DynamicBatcher(iteration_level=True, scheduler=sched)
        b.close()
        with pytest.raises(RuntimeError):
            b.submit(np.zeros((2,), np.int32))


# ---------------------------------------------------------------------------
# ServeMonitorHook: iteration-level counters on the export surface
# ---------------------------------------------------------------------------

class TestContinuousMonitorExport:
    def test_hook_exports_slot_counters(self, gpt2_engine, caplog):
        import logging

        from distributed_tensorflow_tpu.obs import ServeMonitorHook

        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _mixed_requests(vocab, n=4, seed=13)
        with ContinuousScheduler(gpt2_engine, num_slots=8,
                                 max_total_len=32) as sched:
            hook = ServeMonitorHook(sched, every_steps=1)
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            for f in futs:
                f.result(timeout=300)
            m = hook.metrics()
            with caplog.at_level(
                    logging.INFO,
                    logger="distributed_tensorflow_tpu.obs.serve"):
                logged = hook.log(4)
        for key in ("serve_slot_occupancy", "serve_admissions_per_iter",
                    "serve_retirements_per_iter", "serve_ttft_p50_ms",
                    "serve_ttft_p99_ms", "serve_tpot_mean_ms",
                    "serve_iterations", "serve_num_slots"):
            assert key in m, m
        assert logged["serve_completed"] == 4.0
        assert any("occupancy=" in r.message and "ttft_p50=" in r.message
                   for r in caplog.records)
