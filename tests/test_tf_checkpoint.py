"""One-way TF checkpoint interop (VERDICT r3 #8): TF writes a real
tensor-bundle checkpoint with the INSTALLED tensorflow; this repo reads it
back — through the TF-backed reader AND the pure-python bundle parser —
and maps the variables into a params pytree, including stacking per-layer
TF variables into the scanned (L, ...) layout."""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_tpu.checkpoint import (  # noqa: E402
    assign_into_tree,
    load_tf_variables,
    stack_layer_variables,
)
from distributed_tensorflow_tpu.checkpoint.tf_compat import (  # noqa: E402
    TFCheckpointError,
    _PurePythonBundleReader,
)


@pytest.fixture
def tf1_checkpoint(tmp_path):
    """A TF1 Saver checkpoint (the reference's Saver path, saver.py:642)."""
    rng = np.random.RandomState(0)
    values = {
        "dense/kernel": rng.randn(4, 8).astype(np.float32),
        "dense/bias": rng.randn(8).astype(np.float32),
        "embed/table": rng.randn(16, 4).astype(np.float32),
        "global_step": np.int64(42),
    }
    g = tf.Graph()
    with g.as_default():
        for name, val in values.items():
            tf.compat.v1.get_variable(name, initializer=val)
        saver = tf.compat.v1.train.Saver()
        with tf.compat.v1.Session(graph=g) as sess:
            sess.run(tf.compat.v1.global_variables_initializer())
            prefix = saver.save(sess, str(tmp_path / "model.ckpt"),
                                write_meta_graph=False)
    return prefix, values


class TestBundleReaders:
    @pytest.mark.parametrize("pure", [False, True],
                             ids=["tf-backed", "pure-python"])
    def test_reads_tf1_saver_checkpoint(self, tf1_checkpoint, pure):
        prefix, values = tf1_checkpoint
        got = load_tf_variables(prefix, force_pure_python=pure)
        assert sorted(got) == sorted(values)
        for name, want in values.items():
            np.testing.assert_array_equal(got[name], np.asarray(want))

    def test_readers_agree_bytewise(self, tf1_checkpoint):
        prefix, _ = tf1_checkpoint
        a = load_tf_variables(prefix, force_pure_python=True)
        b = load_tf_variables(prefix, force_pure_python=False)
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])

    def test_reads_tf2_object_checkpoint(self, tmp_path):
        """TF2 tf.train.Checkpoint: names get the /.ATTRIBUTES suffix
        stripped and the object-graph entry skipped."""
        w = tf.Variable(np.arange(6, dtype=np.float32).reshape(2, 3),
                        name="w")
        ckpt = tf.train.Checkpoint(w=w)
        prefix = ckpt.write(str(tmp_path / "obj.ckpt"))
        for pure in (False, True):
            got = load_tf_variables(prefix, force_pure_python=pure)
            assert "w" in got, got.keys()  # suffix stripped to the obj path
            np.testing.assert_array_equal(
                got["w"], np.arange(6, dtype=np.float32).reshape(2, 3))

    def test_bf16_variables_decode(self, tmp_path):
        v = tf.Variable(tf.constant([1.5, -2.25, 0.0], tf.bfloat16),
                        name="b16")
        ckpt = tf.train.Checkpoint(v=v)
        prefix = ckpt.write(str(tmp_path / "b16.ckpt"))
        got = load_tf_variables(prefix, force_pure_python=True)
        np.testing.assert_array_equal(got["v"],
                                      np.asarray([1.5, -2.25, 0.0],
                                                 np.float32))

    def test_non_bundle_file_rejected(self, tmp_path):
        bad = tmp_path / "x.index"
        bad.write_bytes(b"\x00" * 64)
        with pytest.raises(TFCheckpointError, match="magic"):
            _PurePythonBundleReader(str(tmp_path / "x"))


class TestMappingIntoTree:
    def test_assign_by_path_with_shape_check(self, tf1_checkpoint):
        prefix, values = tf1_checkpoint
        tf_vars = load_tf_variables(prefix, force_pure_python=True)
        params = {
            "dense": {"kernel": np.zeros((4, 8), np.float32),
                      "bias": np.zeros((8,), np.float32)},
            "embed": {"table": np.zeros((16, 4), np.float32)},
        }
        new = assign_into_tree(params, {
            "dense/kernel": tf_vars["dense/kernel"],
            "dense/bias": tf_vars["dense/bias"],
            "embed/table": tf_vars["embed/table"],
        })
        np.testing.assert_array_equal(np.asarray(new["dense"]["kernel"]),
                                      values["dense/kernel"])
        np.testing.assert_array_equal(np.asarray(new["embed"]["table"]),
                                      values["embed/table"])
        # wrong shape is a loud error, not a silent broadcast
        with pytest.raises(ValueError, match="shape"):
            assign_into_tree(params, {
                "dense/kernel": np.zeros((8, 4), np.float32)})
        with pytest.raises(KeyError):
            assign_into_tree(params, {"nope/kernel": np.zeros(1)})

    def test_stack_per_layer_tf_vars_into_scanned_layout(self, tmp_path):
        """The migration shape that matters for the transformer models:
        TF checkpoints store layer_0..layer_N-1 separately; the scanned
        modules want ONE (L, ...) parameter."""
        L, d = 3, 4
        rng = np.random.RandomState(7)
        per_layer = {
            f"encoder/layer_{i}/attention/kernel":
                rng.randn(d, d).astype(np.float32)
            for i in range(L)
        }
        g = tf.Graph()
        with g.as_default():
            for name, val in per_layer.items():
                tf.compat.v1.get_variable(name, initializer=val)
            saver = tf.compat.v1.train.Saver()
            with tf.compat.v1.Session(graph=g) as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                prefix = saver.save(sess, str(tmp_path / "bert.ckpt"),
                                    write_meta_graph=False)
        tf_vars = load_tf_variables(prefix, force_pure_python=True)
        stacked = stack_layer_variables(
            tf_vars, "encoder/layer_{i}/attention/kernel", L)
        assert stacked.shape == (L, d, d)
        params = {"layers": {"attention": {
            "kernel": np.zeros((L, d, d), np.float32)}}}
        new = assign_into_tree(
            params, {"layers/attention/kernel": stacked})
        for i in range(L):
            np.testing.assert_array_equal(
                np.asarray(new["layers"]["attention"]["kernel"])[i],
                per_layer[f"encoder/layer_{i}/attention/kernel"])

    def test_restore_into_live_workload_params(self, tmp_path):
        """End-to-end: TF writes the variables of the mnist CNN's shapes;
        they land in the real workload's params tree and a forward pass
        runs on them."""
        import jax

        from distributed_tensorflow_tpu.models import get_workload

        wl = get_workload("mnist", batch_size=8)
        variables = wl.module.init(jax.random.key(0), wl.init_batch["image"])
        params = variables["params"]
        flat = {}

        def _walk(prefix, node):
            if isinstance(node, dict):
                for k, v in node.items():
                    _walk(f"{prefix}/{k}" if prefix else k, v)
            else:
                flat[prefix] = np.asarray(node)

        _walk("", params)
        rng = np.random.RandomState(3)
        tf_values = {k: rng.randn(*v.shape).astype(np.float32) * 0.05
                     for k, v in flat.items()}
        g = tf.Graph()
        with g.as_default():
            for name, val in tf_values.items():
                tf.compat.v1.get_variable(name, initializer=val)
            saver = tf.compat.v1.train.Saver()
            with tf.compat.v1.Session(graph=g) as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                prefix = saver.save(sess, str(tmp_path / "mnist.ckpt"),
                                    write_meta_graph=False)
        tf_vars = load_tf_variables(prefix, force_pure_python=True)
        new_params = assign_into_tree(params, tf_vars)
        logits = wl.module.apply({"params": new_params},
                                 wl.init_batch["image"])
        assert np.isfinite(np.asarray(logits)).all()
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(new_params)[0]),
            tf_values[sorted(flat)[0]], rtol=1e-6)


class TestPartitionedVariables:
    """The reference's PS partitioner case (sharded_variable.py:84):
    fixed_size_partitioner writes one logical variable as OrderedCode-keyed
    slices; both readers reassemble the full tensor."""

    @pytest.mark.parametrize("pure", [False, True],
                             ids=["tf-backed", "pure-python"])
    def test_reassembles_partitioned_variable(self, tmp_path, pure):
        g = tf.Graph()
        with g.as_default():
            v = tf.compat.v1.get_variable(
                "emb/table", shape=(16, 4), dtype=tf.float32,
                partitioner=tf.compat.v1.fixed_size_partitioner(4),
                initializer=tf.compat.v1.truncated_normal_initializer(
                    seed=11))
            saver = tf.compat.v1.train.Saver()
            with tf.compat.v1.Session(graph=g) as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                full = sess.run(tf.convert_to_tensor(v))  # concatenated
                prefix = saver.save(sess, str(tmp_path / "part.ckpt"),
                                    write_meta_graph=False)
        got = load_tf_variables(prefix, force_pure_python=pure)
        assert "emb/table" in got
        np.testing.assert_array_equal(got["emb/table"], full)
