"""Model-family tests (SURVEY.md §3.5): each reference workload builds,
shards over a virtual mesh, and trains (loss decreases / stays finite).

Tiny configs keep CPU runtime low; the architectures are the real ones.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data import per_host_batch_size
from distributed_tensorflow_tpu.data.pipeline import make_global_batches
from distributed_tensorflow_tpu.models import get_workload
from distributed_tensorflow_tpu.train_lib import build_state_and_step
from distributed_tensorflow_tpu.training import FP32


def run_steps(workload, mesh, n_steps, *, precision=FP32, grad_accum=1):
    state, state_sh, train_step, batch_sh = build_state_and_step(
        workload, mesh, precision=precision,
        grad_accum_steps=grad_accum, total_steps=n_steps,
    )
    host_iter = workload.data_fn(per_host_batch_size(workload.batch_size))
    sh = batch_sh[workload.example_key]
    data = make_global_batches(host_iter, sh)
    # Constant base key: the step folds state.step in on device
    # (build_state_and_step builds in_step_rng=True steps).
    rng = jax.random.key(1)
    metrics_hist = []
    for i, batch in zip(range(n_steps), data):
        state, metrics = train_step(state, batch, rng)
        metrics_hist.append({k: float(v) for k, v in metrics.items()})
    return state, metrics_hist


class TestResNet:
    def test_tiny_resnet_trains_on_dp_mesh(self, mesh_dp):
        wl = get_workload(
            "resnet50", batch_size=16, num_classes=10, image_size=32,
            stage_sizes=(1, 1, 1, 1), learning_rate=0.025,
            # 8 steps on a random stream: per-step crop/flip variance
            # swamps the loss-decrease signal; augmentation correctness
            # has its own test below
            augment=False,
        )
        state, hist = run_steps(wl, mesh_dp, 8)
        losses = [m["loss"] for m in hist]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_batch_stats_update_and_are_finite(self, mesh_dp):
        wl = get_workload(
            "resnet50", batch_size=8, num_classes=4, image_size=32,
            stage_sizes=(1, 1, 1, 1),
        )
        state, _ = run_steps(wl, mesh_dp, 2)
        stats = state.model_state["batch_stats"]
        leaves = jax.tree.leaves(stats)
        assert leaves, "batch_stats collection missing"
        means = [np.asarray(x) for x in jax.tree.leaves(stats)]
        assert all(np.isfinite(m).all() for m in means)
        # running stats must have moved away from init (mean 0 / var 1)
        moved = any(float(np.abs(m).sum()) > 0 for m in means[:1])
        assert moved

    def test_eval_uses_running_stats(self, mesh_dp):
        from distributed_tensorflow_tpu.training import make_eval_step

        wl = get_workload(
            "resnet50", batch_size=8, num_classes=4, image_size=32,
            stage_sizes=(1, 1, 1, 1),
        )
        state, _ = run_steps(wl, mesh_dp, 2)
        eval_step = make_eval_step(wl.eval_loss_fn, precision=FP32,
                                   stateful=True)
        batch = next(wl.data_fn(8))
        # batch-size-1 eval: per-batch BN stats would collapse activations;
        # running averages must give finite, batch-size-independent output.
        one = {k: v[:1] for k, v in batch.items()}
        m1 = eval_step(state, jax.tree.map(jnp.asarray, one), jax.random.key(0))
        m8 = eval_step(state, jax.tree.map(jnp.asarray, batch), jax.random.key(0))
        assert np.isfinite(float(m1["loss"])) and np.isfinite(float(m8["loss"]))

    def test_augmentation_train_only_and_per_step(self, mesh_dp):
        """VERDICT r4 missing #2: the ResNet recipe's random crop+flip runs
        device-side in the compiled TRAIN step (fresh per step rng), never
        at eval, and preserves uint8 staging."""
        from distributed_tensorflow_tpu.models.resnet import quantize_images
        from distributed_tensorflow_tpu.train_lib import _wrap_from_record

        wl = get_workload(
            "resnet50", batch_size=8, num_classes=4, image_size=32,
            stage_sizes=(1, 1, 1, 1),
        )
        assert wl.augment_fn is not None
        raw = next(wl.data_fn(8))
        staged = {k: jnp.asarray(v) for k, v in quantize_images(raw).items()}
        assert staged["image"].dtype == jnp.uint8

        # deterministic in rng, varying across rngs, dtype-preserving
        a1 = wl.augment_fn(staged, jax.random.key(1))["image"]
        a2 = wl.augment_fn(staged, jax.random.key(2))["image"]
        a1b = wl.augment_fn(staged, jax.random.key(1))["image"]
        assert a1.dtype == jnp.uint8
        assert not np.array_equal(np.asarray(a1), np.asarray(a2))
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a1b))

        # train loss sees different views per step rng; eval loss does not
        variables = dict(wl.module.init(jax.random.key(0),
                                        wl.init_batch["image"]))
        params = variables.pop("params")
        train_fn = _wrap_from_record(wl, wl.loss_fn, train=True)
        eval_fn = _wrap_from_record(wl, wl.eval_loss_fn)
        lt1 = float(train_fn(params, variables, staged,
                             jax.random.key(1))[0])
        lt2 = float(train_fn(params, variables, staged,
                             jax.random.key(2))[0])
        le1 = float(eval_fn(params, variables, staged,
                            jax.random.key(1))[0])
        le2 = float(eval_fn(params, variables, staged,
                            jax.random.key(2))[0])
        assert lt1 != lt2  # augmentation varies the training view
        assert le1 == le2  # eval is augmentation-free and deterministic

    def test_resnet50_full_architecture_param_count_marker(self):
        # Real ResNet-50 head count: ~25.6M params. Shape-eval only (fast).
        wl = get_workload("resnet50")
        import jax

        def init():
            return wl.module.init(
                jax.random.key(0), wl.init_batch["image"]
            )

        shapes = jax.eval_shape(init)
        n_params = sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"])
        )
        assert 25_000_000 < n_params < 26_000_000, n_params


class TestGPT2:
    def _tiny(self, **kw):
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        return get_workload(
            "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
            grad_accum_steps=kw.pop("grad_accum_steps", 1), **kw,
        )

    def test_tiny_gpt2_trains(self, mesh_dp):
        wl = self._tiny()
        state, hist = run_steps(wl, mesh_dp, 10)
        losses = [m["loss"] for m in hist]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_tensor_parallel_sharding_applied(self, mesh_2d):
        wl = self._tiny()
        state, hist = run_steps(wl, mesh_2d, 2)
        # scanned layout: stacked qkv kernel (L, d, 3d); layer dim
        # unsharded, tensor axis on the output dim
        qkv = state.params["blocks"]["c_attn"]["kernel"]
        assert qkv.ndim == 3
        spec = qkv.sharding.spec
        assert "tensor" in tuple(x for x in spec if x), spec
        # layer dim rides the pipe axis (trivial at pipe=1)
        assert spec[0] in (None, (), "pipe"), spec
        assert np.isfinite(hist[-1]["loss"])

    def test_unscanned_layout_still_works(self, mesh_2d):
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        wl = get_workload(
            "gpt2",
            config=GPT2Config.tiny(scan_layers=False, remat=False),
            batch_size=8, seq_len=32, grad_accum_steps=1,
        )
        state, hist = run_steps(wl, mesh_2d, 2)
        qkv = state.params["h_0"]["c_attn"]["kernel"]
        assert "tensor" in tuple(x for x in qkv.sharding.spec if x)
        assert np.isfinite(hist[-1]["loss"])

    def test_tp_matches_dp_loss(self, mesh_dp, mesh_2d):
        # Same model/data: pure-DP loss and TP+DP loss must agree closely —
        # the TP decomposition is mathematically the same program.
        l_dp = [m["loss"] for m in run_steps(self._tiny(), mesh_dp, 3)[1]]
        l_tp = [m["loss"] for m in run_steps(self._tiny(), mesh_2d, 3)[1]]
        np.testing.assert_allclose(l_dp, l_tp, rtol=2e-2)

    def test_context_parallel_with_data4_mesh_inits(self):
        # regression: init batch must divide over data axes when the mesh
        # forces the ring-attention shard_map path (data=4, context=2)
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh = build_mesh(MeshConfig(data=4, context=2), jax.devices())
        wl = get_workload(
            "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
            grad_accum_steps=1, mesh=mesh,
        )
        state, hist = run_steps(wl, mesh, 2)
        assert np.isfinite(hist[-1]["loss"])

    def test_context_parallel_ring_attention_matches_dp(self, mesh_dp, mesh_4d):
        # mesh_4d has context=2: GPT-2 switches to ring attention. Loss must
        # match the dense-attention DP run (exact attention either way).
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        def make(mesh):
            return get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                grad_accum_steps=1, mesh=mesh,
            )

        l_dp = [m["loss"] for m in run_steps(make(None), mesh_dp, 3)[1]]
        l_cp = [m["loss"] for m in run_steps(make(mesh_4d), mesh_4d, 3)[1]]
        np.testing.assert_allclose(l_dp, l_cp, rtol=2e-2)

    def test_grad_accum_runs(self, mesh_dp):
        wl = self._tiny(grad_accum_steps=2)
        state, hist = run_steps(wl, mesh_dp, 3, grad_accum=2)
        assert np.isfinite([m["loss"] for m in hist]).all()

    def test_context_parallel_chunked_ring_matches_dp(self, mesh_dp, mesh_4d):
        # ring_chunk_size < per-shard block: the chunked (bounded-memory)
        # ring path through the workload override must match DP loss.
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        def make(mesh, **kw):
            return get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                grad_accum_steps=1, mesh=mesh, **kw,
            )

        l_dp = [m["loss"] for m in run_steps(make(None), mesh_dp, 3)[1]]
        l_cp = [m["loss"] for m in run_steps(
            make(mesh_4d, ring_chunk_size=8), mesh_4d, 3)[1]]
        np.testing.assert_allclose(l_dp, l_cp, rtol=2e-2)

    def test_microbatch_must_divide_batch_axes_on_ring_mesh(self, mesh_4d):
        # On a context>1 mesh (the shard_map ring path), batch 8 /
        # accum 8 = microbatch 1 cannot divide data*fsdp=2: a clear error
        # instead of a cryptic shard_map divisibility failure.
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        wl = get_workload(
            "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
            grad_accum_steps=8, mesh=mesh_4d,
        )
        with pytest.raises(ValueError, match="microbatch"):
            build_state_and_step(wl, mesh_4d, grad_accum_steps=8,
                                 total_steps=2)

    def test_pipeline_parallel_matches_dp_loss(self, mesh_dp):
        # data=2 x tensor=2 x pipe=2: the GPipe schedule + TP inside stages
        # must reproduce the pure-DP loss trajectory (same math, reordered).
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh_pp = build_mesh(
            MeshConfig(data=2, tensor=2, pipe=2), jax.devices()
        )

        def make(mesh):
            return get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                grad_accum_steps=1, mesh=mesh,
            )

        l_dp = [m["loss"] for m in run_steps(make(None), mesh_dp, 3)[1]]
        l_pp = [m["loss"] for m in run_steps(make(mesh_pp), mesh_pp, 3)[1]]
        np.testing.assert_allclose(l_dp, l_pp, rtol=2e-2)

    def test_pipe_1f1b_matches_gpipe_loss(self):
        """--pipe_schedule=1f1b trains the flagship through the combined
        fwd/bwd 1F1B scan (custom_vjp hands precomputed grads to the
        standard step); its loss trajectory must match GPipe's (same math,
        different schedule + remat)."""
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh = build_mesh(MeshConfig(data=2, tensor=2, pipe=2),
                          jax.devices())

        def losses(schedule):
            wl = get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                grad_accum_steps=1, mesh=mesh, pipe_schedule=schedule,
            )
            return [m["loss"] for m in run_steps(wl, mesh, 3)[1]]

        np.testing.assert_allclose(losses("gpipe"), losses("1f1b"),
                                   rtol=2e-2)

    def test_pipe_1f1b_composes_with_grad_accum(self):
        """grad_accum scans the custom_vjp 1F1B loss over accumulation
        microbatches — the composition must train with finite loss and
        match the accum=1 trajectory (same total batch, same math)."""
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh = build_mesh(MeshConfig(data=2, pipe=2), jax.devices()[:4])

        def losses(accum):
            wl = get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                grad_accum_steps=accum, mesh=mesh, pipe_schedule="1f1b",
            )
            return [m["loss"] for m in run_steps(wl, mesh, 2)[1]]

        np.testing.assert_allclose(losses(1), losses(2), rtol=2e-2)

    def test_pipeline_stage_params_sharded_over_pipe(self):
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh = build_mesh(MeshConfig(data=4, pipe=2), jax.devices())
        wl = get_workload(
            "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
            grad_accum_steps=1, mesh=mesh,
        )
        state, hist = run_steps(wl, mesh, 2)
        qkv = state.params["blocks"]["c_attn"]["kernel"]
        assert qkv.sharding.spec[0] == "pipe", qkv.sharding.spec
        assert np.isfinite(hist[-1]["loss"])

    def test_pipe_with_context_rejected(self):
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2Config

        mesh = build_mesh(MeshConfig(data=2, pipe=2, context=2),
                          jax.devices())
        with pytest.raises(ValueError, match="pipe.*context|context.*pipe"):
            get_workload(
                "gpt2", config=GPT2Config.tiny(), batch_size=8, seq_len=32,
                mesh=mesh,
            )

    def test_chunked_ce_matches_full_logits(self):
        """ce_chunk computes the same loss AND grads as the full (B, T, V)
        logits path while never materializing it (peak = one (B, chunk, V)
        tile under a rematerialized scan)."""
        import dataclasses

        from distributed_tensorflow_tpu.models.gpt2 import (
            GPT2,
            GPT2Config,
            _loss_fn,
        )

        cfg = GPT2Config.tiny(dtype=jnp.float32)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (4, 128)), jnp.int32)
        batch = {"tokens": tokens}
        m_full = GPT2(cfg)
        m_chunk = GPT2(dataclasses.replace(cfg, ce_chunk=32))
        params = m_full.init(jax.random.key(0), tokens)["params"]
        l1, g1 = jax.value_and_grad(
            lambda p: _loss_fn(m_full, True, p, batch, None)[0])(params)
        l2, g2 = jax.value_and_grad(
            lambda p: _loss_fn(m_chunk, True, p, batch, None)[0])(params)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
            g1, g2,
        )

    def test_dense_oom_config_raises_actionable_error(self):
        """VERDICT r2 weak #3: the flagship config without flash must not
        hit a silent XLA RESOURCE_EXHAUSTED — make_workload refuses it and
        names the fixes."""
        with pytest.raises(ValueError, match="flash_attention"):
            get_workload(
                "gpt2", preset="medium", batch_size=16, seq_len=1024,
                grad_accum_steps=1, use_flash_attention=False,
            )
        # the reference's own answer (accum 4 -> microbatch 4) still builds
        wl = get_workload(
            "gpt2", preset="medium", batch_size=16, seq_len=1024,
            grad_accum_steps=4, use_flash_attention=False,
        )
        assert wl.grad_accum_steps == 4
        # and flash at accum 1 builds (no (T, T) buffer)
        get_workload(
            "gpt2", preset="medium", batch_size=16, seq_len=1024,
            grad_accum_steps=1, use_flash_attention=True,
        )

    def test_gpt2_medium_config_param_count(self):
        from distributed_tensorflow_tpu.models.gpt2 import GPT2, GPT2Config

        cfg = GPT2Config.medium()
        module = GPT2(cfg)

        def init():
            return module.init(
                jax.random.key(0), np.zeros((1, 8), np.int32)
            )

        shapes = jax.eval_shape(init)
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))
        # GPT-2 medium: ~354.8M (tied head)
        assert 350_000_000 < n < 360_000_000, n


class TestBert:
    def _tiny(self, **kw):
        from distributed_tensorflow_tpu.models.bert import BertConfig

        return get_workload(
            "bert", config=BertConfig.tiny(), batch_size=8, seq_len=32, **kw,
        )

    def test_tiny_bert_trains(self, mesh_dp):
        wl = self._tiny()
        state, hist = run_steps(wl, mesh_dp, 10)
        losses = [m["loss"] for m in hist]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert "mlm_loss" in hist[0] and "nsp_loss" in hist[0]

    def test_bert_tp_mesh(self, mesh_2d):
        wl = self._tiny()
        state, hist = run_steps(wl, mesh_2d, 2)
        qkv = state.params["layers"]["qkv"]["kernel"]  # scanned: (L, d, 3d)
        assert qkv.ndim == 3
        assert "tensor" in tuple(x for x in qkv.sharding.spec if x)
        assert np.isfinite(hist[-1]["loss"])

    def test_bert_context_parallel_ring_matches_dp(self, mesh_dp, mesh_4d):
        # mesh_4d has context=2: BERT switches to non-causal ring attention.
        # Loss must match the dense-attention DP run (exact either way).
        from distributed_tensorflow_tpu.models.bert import BertConfig

        def make(mesh):
            return get_workload(
                "bert", config=BertConfig.tiny(), batch_size=8, seq_len=32,
                mesh=mesh,
            )

        l_dp = [m["loss"] for m in run_steps(make(None), mesh_dp, 3)[1]]
        l_cp = [m["loss"] for m in run_steps(make(mesh_4d), mesh_4d, 3)[1]]
        np.testing.assert_allclose(l_dp, l_cp, rtol=2e-2)

    def test_masked_paths_agree(self, mesh_4d, monkeypatch):
        """VERDICT r2 #1 done-criterion: with variable-length masked
        batches, the dense, flash (interpreter), and ring attention paths
        produce the same loss and gradients (f32, so exact)."""
        import dataclasses

        monkeypatch.setenv("DTT_PALLAS_INTERPRET", "1")
        from distributed_tensorflow_tpu.data.pipeline import synthetic_mlm
        from distributed_tensorflow_tpu.models.bert import (
            BertConfig,
            BertPretrain,
            _loss_fn,
        )

        cfg = BertConfig.tiny(dtype=jnp.float32)
        batch = next(synthetic_mlm(batch_size=8, seq_len=64, vocab_size=256))
        lengths = batch["input_mask"].sum(1)
        assert lengths.min() < 64, "variable lengths expected"
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        params = BertPretrain(cfg).init(jax.random.key(0), batch)["params"]

        def loss_for(c, mesh=None):
            m = BertPretrain(c, mesh=mesh)
            return lambda p: _loss_fn(m, True, p, batch, None)[0]

        l_dense, g_dense = jax.value_and_grad(loss_for(cfg))(params)
        l_flash, g_flash = jax.value_and_grad(loss_for(
            dataclasses.replace(cfg, use_flash_attention=True)))(params)
        l_ring, g_ring = jax.jit(jax.value_and_grad(
            loss_for(cfg, mesh_4d)))(params)
        np.testing.assert_allclose(float(l_dense), float(l_flash), rtol=1e-6)
        np.testing.assert_allclose(float(l_dense), float(l_ring), rtol=1e-6)
        for other in (g_flash, g_ring):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
                g_dense, other,
            )

    def test_synthetic_mlm_mask_invariants(self):
        """Variable-length batches: mask is a contiguous prefix, padded
        tokens are 0, and every MLM prediction slot is a valid position."""
        from distributed_tensorflow_tpu.data.pipeline import synthetic_mlm

        batch = next(synthetic_mlm(batch_size=16, seq_len=64, vocab_size=256))
        mask = batch["input_mask"]
        lengths = mask.sum(1)
        assert lengths.min() >= 32 and lengths.max() <= 64
        assert len(set(lengths.tolist())) > 1, "lengths should vary"
        # prefix property
        assert (mask == (np.arange(64)[None, :] < lengths[:, None])).all()
        assert (batch["tokens"] * (1 - mask) == 0).all()
        assert (batch["mlm_positions"] < lengths[:, None]).all()
        # segments: 0 before the midpoint, 1 from midpoint to length
        seg = batch["segment_ids"]
        assert (seg[:, :32] == 0).all()
        assert (seg * (1 - mask) == 0).all()

    def test_mask_changes_output(self):
        """Padding must actually be invisible: attention output at valid
        positions is identical whether padded slots hold zeros or junk."""
        from distributed_tensorflow_tpu.models.bert import (
            BertConfig,
            BertPretrain,
        )

        cfg = BertConfig.tiny(dtype=jnp.float32)
        rng = np.random.RandomState(5)
        T, L = 32, 20
        base = {
            "tokens": rng.randint(2, 256, size=(2, T)).astype(np.int32),
            "input_mask": (np.arange(T)[None, :] < L).astype(np.int32)
            * np.ones((2, 1), np.int32),
            "mlm_positions": np.zeros((2, 4), np.int32),
            "segment_ids": np.zeros((2, T), np.int32),
        }
        junk = dict(base)
        junk["tokens"] = base["tokens"].copy()
        junk["tokens"][:, L:] = rng.randint(2, 256, size=(2, T - L))
        module = BertPretrain(cfg)
        params = module.init(jax.random.key(0), base)["params"]
        out_base, _ = module.apply({"params": params}, base)
        out_junk, _ = module.apply({"params": params}, junk)
        np.testing.assert_allclose(
            np.asarray(out_base), np.asarray(out_junk), atol=1e-6)

    def test_bert_base_param_count(self):
        from distributed_tensorflow_tpu.models.bert import (
            BertConfig,
            BertPretrain,
        )

        module = BertPretrain(BertConfig.base())
        batch = {
            "tokens": np.zeros((1, 8), np.int32),
            "mlm_positions": np.zeros((1, 2), np.int32),
            "mlm_targets": np.zeros((1, 2), np.int32),
            "mlm_weights": np.zeros((1, 2), np.float32),
            "segment_ids": np.zeros((1, 8), np.int32),
            "nsp_label": np.zeros((1,), np.int32),
        }

        def init():
            return module.init(jax.random.key(0), batch)

        shapes = jax.eval_shape(init)
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))
        # BERT-base: ~110M
        assert 105_000_000 < n < 115_000_000, n
