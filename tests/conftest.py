"""Test harness: force an 8-device virtual CPU platform before jax imports.

Mirrors the reference stack's test methodology tier (b) (SURVEY.md §5):
simulated multi-device meshes without hardware, via
``--xla_force_host_platform_device_count``.

The axon TPU plugin registers itself from sitecustomize at interpreter
startup — before pytest imports this file — so it cannot be disabled here
(only a shell-level ``PALLAS_AXON_POOL_IPS=`` before launching python can do
that). What forces CPU for the test run is ``jax.config.update`` below, plus
XLA_FLAGS being set before the CPU backend is first touched.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 forced CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh_dp(devices8):
    """Pure data-parallel mesh: data=8."""
    return build_mesh(MeshConfig(), devices8)


@pytest.fixture(scope="session")
def mesh_2d(devices8):
    """data=4 x tensor=2."""
    return build_mesh(MeshConfig(data=4, tensor=2), devices8)


@pytest.fixture(scope="session")
def mesh_4d(devices8):
    """data=2 x tensor=2 x pipe=1 x context=2 (exercises several axes)."""
    return build_mesh(MeshConfig(data=2, tensor=2, context=2), devices8)
