"""Prefix caching tests: chained content keys, the refcounted allocator
(sharing, eviction, invalidation, the double-free guard), and the parity
oracle — greedy streams with ``prefix_cache`` on are bit-identical to the
uncached paged path on both acceptance meshes, including copy-on-write
divergence and eviction under pool pressure.

Parity is exact array equality: a cache hit maps the very blocks an
uncached run would have recomputed, and the deterministic forward writes
the same bits into them, so any drift is a sharing bug — not noise.
"""

import numpy as np
import pytest

from distributed_tensorflow_tpu.serve import ContinuousScheduler, ServeEngine
from distributed_tensorflow_tpu.serve.paged import (
    BlockAllocator,
    BlockExhaustedError,
    chain_block_keys,
)


def _fixed_reference(engine, prompt, max_new_tokens):
    rows = engine.bucket_rows(1)
    out = engine.generate(np.repeat(prompt[None, :], rows, axis=0),
                          max_new_tokens)
    return out[0]


def _shared_prefix_requests(vocab, *, prefix_len=16, groups=2, n=8, seed=2):
    """n requests cycling over ``groups`` distinct system prompts, each
    with its own random tail (mixed lengths/horizons)."""
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab, size=(prefix_len,), dtype=np.int32)
                for _ in range(groups)]
    reqs = []
    for i in range(n):
        tail_len = (4, 6, 5, 8)[i % 4]
        horizon = (5, 3, 4, 6)[i % 4]
        tail = rng.integers(0, vocab, size=(tail_len,), dtype=np.int32)
        reqs.append((np.concatenate([prefixes[i % groups], tail]), horizon))
    return reqs


@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


# ---------------------------------------------------------------------------
# Chained content keys
# ---------------------------------------------------------------------------

class TestChainBlockKeys:
    def test_full_blocks_only(self):
        toks = np.arange(11, dtype=np.int32)
        assert len(chain_block_keys(toks, 4)) == 2  # trailing 3 dropped
        assert chain_block_keys(toks[:3], 4) == []

    def test_deterministic_and_prefix_sensitive(self):
        toks = np.arange(12, dtype=np.int32)
        a = chain_block_keys(toks, 4)
        assert a == chain_block_keys(toks.copy(), 4)
        # mutating block 0 changes EVERY downstream key (chained hashes)
        other = toks.copy()
        other[0] += 1
        b = chain_block_keys(other, 4)
        assert all(x != y for x, y in zip(a, b))
        # mutating the last block leaves the earlier chain intact
        other = toks.copy()
        other[-1] += 1
        c = chain_block_keys(other, 4)
        assert c[:2] == a[:2] and c[2] != a[2]


# ---------------------------------------------------------------------------
# Refcounted allocator + prefix map: pure host-side unit tests
# ---------------------------------------------------------------------------

class TestPrefixAllocator:
    def test_refcounted_sharing_and_release(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        blocks = a.allocate(2, slot=0)
        keys = chain_block_keys(np.arange(8), 4)
        assert a.register_prefix(blocks, keys) == 2
        got = a.acquire_prefix(keys)
        assert got == blocks
        assert [a.ref_count(b) for b in blocks] == [2, 2]
        assert a.used_count == 2  # shared, not duplicated
        a.free(blocks)            # first holder retires
        assert [a.ref_count(b) for b in blocks] == [1, 1]
        assert a.used_count == 2
        a.free(blocks)            # last holder: park on the evictable LRU
        assert a.used_count == 0
        assert a.evictable_count == 2
        assert a.free_count == a.capacity - 2
        # still cached: a new request revives them without reallocation
        assert a.acquire_prefix(keys) == blocks

    def test_double_free_guard(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        blocks = a.allocate(2)
        a.free(blocks)
        with pytest.raises(ValueError, match="double free"):
            a.free([blocks[0]])
        # freeing a block that was never allocated is the same bug
        with pytest.raises(ValueError, match="double free"):
            a.free([5])
        # a parked (evictable) block has zero refs — freeing it again is
        # a double free too, not a silent LIFO corruption
        held = a.allocate(1)
        a.register_prefix(held, chain_block_keys(np.arange(4), 4))
        a.free(held)
        assert a.evictable_count == 1
        with pytest.raises(ValueError, match="double free"):
            a.free(held)

    def test_lru_eviction_under_pressure(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        all_blocks = a.allocate(7)
        keyed = {b: chain_block_keys(np.arange(i * 4, i * 4 + 4), 4)
                 for i, b in enumerate(all_blocks[:3])}
        for b, keys in keyed.items():
            a.register_prefix([b], keys)
        a.free(all_blocks)  # 3 park evictable (free order = LRU order), 4 free
        assert a.evictable_count == 3 and a.free_count == 4
        # need 5: four off the free list + ONE eviction — the LRU victim
        # is the first-parked registered block
        a.allocate(5)
        assert a.prefix_evictions == 1
        victim, survivor = all_blocks[0], all_blocks[1]
        assert a.lookup_prefix(keyed[victim]) == 0
        assert a.lookup_prefix(keyed[survivor]) == 1

    def test_exhaustion_counts_evictable_as_available(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        held = a.allocate(2)
        a.register_prefix(held, chain_block_keys(np.arange(8), 4))
        a.free(held)
        # 5 free + 2 evictable = 7 available; 8 is one too many
        with pytest.raises(BlockExhaustedError, match="only 7/7 free"):
            a.allocate(8)
        assert a.evictable_count == 2  # the failed call evicted nothing
        assert len(a.allocate(7)) == 7  # full capacity via eviction
        assert a.prefix_evictions == 2

    def test_invalidate_returns_evictable_to_free_list(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        live = a.allocate(1)
        parked = a.allocate(2)
        keys = chain_block_keys(np.arange(12), 4)
        a.register_prefix(live + parked, keys)
        a.free(parked)
        assert a.invalidate_prefix_cache() == 3
        assert a.cached_block_count == 0
        assert a.evictable_count == 0
        assert a.free_count == a.capacity - 1  # the live block stays out
        assert a.lookup_prefix(keys) == 0
        a.free(live)  # unregistered now: straight back to the free list
        assert a.free_count == a.capacity

    def test_register_requires_live_block(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        with pytest.raises(ValueError, match="unallocated"):
            a.register_prefix([3], chain_block_keys(np.arange(4), 4))

    def test_register_is_idempotent_first_writer_wins(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        keys = chain_block_keys(np.arange(4), 4)
        first, second = a.allocate(1), a.allocate(1)
        assert a.register_prefix(first, keys) == 1
        assert a.register_prefix(first, keys) == 0   # already registered
        assert a.register_prefix(second, keys) == 0  # key taken: skipped
        assert a.acquire_prefix(keys) == first
        a.free(first)  # drop the acquire's ref; holders still live

    def test_stats_surface(self):
        a = BlockAllocator(num_blocks=8, block_size=4)
        held = a.allocate(2)
        a.register_prefix(held, chain_block_keys(np.arange(8), 4))
        a.free(held)
        s = a.stats()
        assert s["blocks_in_use"] == 0.0
        assert s["blocks_evictable"] == 2.0
        assert s["prefix_cached_blocks"] == 2.0
        assert s["prefix_evictions"] == 0.0


# ---------------------------------------------------------------------------
# Parity oracle: prefix_cache on == off, token for token
# ---------------------------------------------------------------------------

def _run_scheduler(engine, reqs, *, sequential=False, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_total_len", 32)
    kw.setdefault("cache_mode", "paged")
    kw.setdefault("block_size", 4)
    with ContinuousScheduler(engine, **kw) as sched:
        if sequential:
            outs = [sched.submit(p, max_new_tokens=m).result(timeout=300)
                    for p, m in reqs]
        else:
            futs = [sched.submit(p, max_new_tokens=m) for p, m in reqs]
            outs = [f.result(timeout=300) for f in futs]
        stats = sched.stats()
    return outs, stats


class TestPrefixParity:
    def test_shared_prefix_traffic_parity_mesh_dp(self, gpt2_engine):
        """THE acceptance property: the same shared-prefix mix, with and
        without the cache, produces identical greedy streams — and the
        cached run actually hit."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _shared_prefix_requests(vocab, n=10)
        off, _ = _run_scheduler(gpt2_engine, reqs, prefix_cache=False)
        on, s = _run_scheduler(gpt2_engine, reqs, prefix_cache=True)
        for a, b in zip(off, on):
            np.testing.assert_array_equal(a, b)
        assert s["prefix_hits"] > 0
        assert s["prefill_tokens_skipped"] > 0
        assert 0.0 < s["prefix_hit_rate"] <= 1.0
        assert s["blocks_in_use"] == 0.0  # all references released

    def test_cow_divergence_shares_then_splits(self, gpt2_engine):
        """Two requests agree for 4 blocks then diverge inside block 5;
        sequential submission guarantees the second maps the shared
        blocks and recomputes the divergent one privately (COW) — both
        streams must match the fixed-batch reference."""
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(3)
        base = rng.integers(0, vocab, size=(22,), dtype=np.int32)
        fork = base.copy()
        fork[18] = (fork[18] + 1) % vocab  # diverge inside block 4
        reqs = [(base, 5), (fork, 5)]
        outs, s = _run_scheduler(gpt2_engine, reqs, sequential=True,
                                 prefix_cache=True)
        for (prompt, horizon), out in zip(reqs, outs):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))
        assert s["prefix_hits"] == 4.0  # blocks 0-3 shared, block 4 not

    def test_block_aligned_prompt_recomputes_last_block(self, gpt2_engine):
        """A prompt the cache covers ENTIRELY still prefills its final
        block (prefill must emit the first sampled token), writing a
        private copy — identical identical-prompt streams prove the
        shared copy was never clobbered."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.random.default_rng(4).integers(
            0, vocab, size=(16,), dtype=np.int32)  # exactly 4 blocks
        reqs = [(prompt, 6), (prompt, 6), (prompt, 6)]
        outs, s = _run_scheduler(gpt2_engine, reqs, sequential=True,
                                 prefix_cache=True)
        ref = _fixed_reference(gpt2_engine, prompt, 6)
        for out in outs:
            np.testing.assert_array_equal(out, ref)
        assert s["prefix_hits"] == 6.0  # 3 mappable blocks x 2 hits

    def test_parity_under_tensor_parallel_mesh(self, mesh_2d):
        """Same oracle on data=4 x tensor=2: cached-block K/V is sharded
        over the tensor axis exactly like freshly-prefilled K/V."""
        with ServeEngine("gpt2", mesh=mesh_2d, preset="tiny") as eng:
            vocab = eng.module.cfg.vocab_size
            reqs = _shared_prefix_requests(vocab, n=6, seed=9)
            off, _ = _run_scheduler(eng, reqs, prefix_cache=False)
            on, s = _run_scheduler(eng, reqs, prefix_cache=True)
            for a, b in zip(off, on):
                np.testing.assert_array_equal(a, b)
            assert s["prefix_hits"] > 0

    def test_per_shard_pools_compose(self, gpt2_engine):
        """per_shard_kv + prefix_cache: each shard keys its own map, so
        hits only happen shard-locally — sequential LIFO slot reuse lands
        same-prefix requests on the same shard, and streams still match
        the uncached run."""
        vocab = gpt2_engine.module.cfg.vocab_size
        reqs = _shared_prefix_requests(vocab, groups=1, n=4, seed=5)
        off, _ = _run_scheduler(gpt2_engine, reqs, sequential=True,
                                num_slots=8, per_shard_kv=True,
                                prefix_cache=False)
        on, s = _run_scheduler(gpt2_engine, reqs, sequential=True,
                               num_slots=8, per_shard_kv=True,
                               prefix_cache=True)
        for a, b in zip(off, on):
            np.testing.assert_array_equal(a, b)
        assert s["prefix_hits"] > 0
        assert s["num_shards"] > 1.0


# ---------------------------------------------------------------------------
# Eviction under pressure + hot-reload invalidation
# ---------------------------------------------------------------------------

class TestPrefixEviction:
    def test_eviction_under_pressure_keeps_parity(self, gpt2_engine):
        """A pool too small to cache every retired prompt evicts LRU
        zero-ref blocks to serve new admissions — backpressure behaviour
        (admission, never mid-decode failure) and streams stay identical
        to the uncached run, and a re-visit of an evicted prefix simply
        misses and recomputes."""
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(6)
        distinct = [(rng.integers(0, vocab, size=(8,), dtype=np.int32), 5)
                    for _ in range(6)]
        reqs = distinct + [distinct[0]]  # revisit the first (evicted) prefix
        # 9 usable blocks; each request's worst case is blocks_for(12) = 3
        # and each retirement parks 2 registered prompt blocks.
        kw = dict(max_total_len=16, num_blocks=10, sequential=True)
        off, _ = _run_scheduler(gpt2_engine, reqs, prefix_cache=False, **kw)
        on, s = _run_scheduler(gpt2_engine, reqs, prefix_cache=True, **kw)
        for a, b in zip(off, on):
            np.testing.assert_array_equal(a, b)
        assert s["prefix_evictions"] > 0.0
        assert s["blocks_high_water"] <= 9.0
        for (prompt, horizon), out in zip(reqs, on):
            np.testing.assert_array_equal(
                out, _fixed_reference(gpt2_engine, prompt, horizon))

    def test_hot_reload_invalidates_cache(self, gpt2_engine):
        """A staged weight generation drops every cached key (cached K/V
        is params-dependent): the same prefix misses right after the
        swap, then caches again under the new generation."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.random.default_rng(7).integers(
            0, vocab, size=(18,), dtype=np.int32)
        with ContinuousScheduler(gpt2_engine, num_slots=4, max_total_len=32,
                                 cache_mode="paged", block_size=4,
                                 prefix_cache=True) as sched:
            sched.submit(prompt, max_new_tokens=4).result(timeout=300)
            sched.submit(prompt, max_new_tokens=4).result(timeout=300)
            hits_before = sched.stats()["prefix_hits"]
            assert hits_before == 4.0
            sched.update_params(gpt2_engine.params, generation=123)
            fut = sched.submit(prompt, max_new_tokens=4)
            np.testing.assert_array_equal(
                fut.result(timeout=300),
                _fixed_reference(gpt2_engine, prompt, 4))
            assert fut.generation == 123
            # the post-swap admission found an empty map: no new hits...
            assert sched.stats()["prefix_hits"] == hits_before
            # ...but re-registered, so the NEXT one hits again
            sched.submit(prompt, max_new_tokens=4).result(timeout=300)
            assert sched.stats()["prefix_hits"] == hits_before + 4.0

    def test_prefix_cache_requires_paged_mode(self, gpt2_engine):
        with pytest.raises(ValueError, match="paged"):
            ContinuousScheduler(gpt2_engine, cache_mode="dense",
                                prefix_cache=True, start=False)
