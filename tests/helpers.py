"""Shared test utilities (imported, not collected — no test_ prefix)."""

import json
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence


def stream_fed_losses(wl, mesh, *, steps=2, total_steps=4, seed=1):
    """Tier-c feeding contract shared by the multiprocess worker scripts:
    train ``steps`` steps on IDENTICAL global batches on every host — each
    host generates the FULL stream (shard override 1/0) and contributes
    only the rows its devices own per ``host_batch_layout`` (a replicated
    batch dim: the whole batch; a data-sharded dim: this process's slice).
    Returns the per-step host losses."""
    import jax

    from distributed_tensorflow_tpu.data.pipeline import (
        host_batch_layout,
        set_stream_shard_override,
    )
    from distributed_tensorflow_tpu.train_lib import build_state_and_step
    from distributed_tensorflow_tpu.training import FP32

    state, _, step, batch_sh = build_state_and_step(
        wl, mesh, precision=FP32, total_steps=total_steps)
    bsh = batch_sh[wl.example_key]
    host_bs, _, idx = host_batch_layout(bsh, wl.batch_size)
    set_stream_shard_override(1, 0)
    try:
        stream = wl.data_fn(wl.batch_size)
        losses = []
        rng = jax.random.key(seed)
        for i in range(steps):
            full = next(stream)
            lo = idx * host_bs
            batch = {
                k: jax.make_array_from_process_local_data(
                    bsh, v[lo:lo + host_bs])
                for k, v in full.items()
            }
            state, m = step(state, batch, jax.random.fold_in(rng, i))
            losses.append(float(m["loss"]))
    finally:
        set_stream_shard_override(None)
    return losses


def free_ports(n: int) -> List[int]:
    """Allocate ``n`` distinct free localhost ports.

    All sockets stay open until every port is bound, so two calls cannot
    be handed the same just-released ephemeral port (the p0 == p1 race a
    close-then-rebind helper has).
    """
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def free_port() -> int:
    return free_ports(1)[0]


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_worker_cluster(
    script: str,
    n: int = 2,
    *,
    args: Sequence[str] = (),
    extra_env: Optional[Dict[str, str]] = None,
) -> List[subprocess.Popen]:
    """Start ``n`` worker processes forming a localhost TF_CONFIG cluster.

    Each runs ``script`` via ``python -c`` with JAX pinned to CPU and the
    axon TPU pool disabled — the shared bootstrap contract of every
    multiprocess test.
    """
    ports = free_ports(n)
    cluster = {"worker": [f"localhost:{p}" for p in ports]}
    procs = []
    for idx in range(n):
        env = dict(
            os.environ,
            TF_CONFIG=json.dumps(
                {"cluster": cluster, "task": {"type": "worker", "index": idx}}
            ),
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
        )
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script, *args],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    return procs


def join_workers(procs, *, timeout: int, fail) -> List[str]:
    """communicate() every worker; on any timeout kill ALL and call
    ``fail(msg)``.  Returns per-worker outputs."""
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            fail("worker cluster hung")
            return []
        outs.append(out)
    return outs
