"""Shared test utilities (imported, not collected — no test_ prefix)."""

import socket
from typing import List


def free_ports(n: int) -> List[int]:
    """Allocate ``n`` distinct free localhost ports.

    All sockets stay open until every port is bound, so two calls cannot
    be handed the same just-released ephemeral port (the p0 == p1 race a
    close-then-rebind helper has).
    """
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def free_port() -> int:
    return free_ports(1)[0]
