"""Two trainer processes sharing ONE data service — the full
tf.data-service topology (SURVEY.md §3.4): a 2-worker jax.distributed
cluster where both workers pull disjoint batches from a single input
server instead of striping the record file.
"""

import os
import select
import subprocess
import sys

import pytest

from tests.helpers import REPO, join_workers, spawn_worker_cluster

TRAINER_SCRIPT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from distributed_tensorflow_tpu.train_lib import TrainArgs, run

result = run(TrainArgs(
    model="mnist", steps=6, batch_size=64, log_every=3,
    data_service=sys.argv[1],
))
assert result["final_step"] == 6, result
assert np.isfinite(result["loss"]), result
print("TRAINER_OK", jax.process_index(), flush=True)
# skip the jax.distributed atexit shutdown barrier races on CPU test exits
os._exit(0)
"""


def test_two_trainers_one_data_service(tmp_path):
    from distributed_tensorflow_tpu.data.records import (
        record_path,
        stage_synthetic_to_records,
    )
    from distributed_tensorflow_tpu.models import get_workload

    wl = get_workload("mnist", batch_size=64)
    stage_synthetic_to_records(
        wl, record_path(str(tmp_path), "mnist"), 512
    )
    svc_env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    # per-host batch for a 2-worker cluster with global batch 64 is 32
    service = subprocess.Popen(
        [sys.executable, "-m", "distributed_tensorflow_tpu.data.service",
         "--model=mnist", f"--data_dir={tmp_path}", "--batch_size=32"],
        env=svc_env, cwd=REPO, stdout=subprocess.PIPE, text=True,
    )
    try:
        ready, _, _ = select.select([service.stdout], [], [], 120)
        if not ready:
            pytest.fail("data service never became ready")
        line = service.stdout.readline()
        assert line.startswith("DATA_SERVICE_READY"), line
        target = line.split()[1]

        trainers = spawn_worker_cluster(TRAINER_SCRIPT, 2, args=(target,))
        outs = join_workers(trainers, timeout=300, fail=pytest.fail)
        for i, (p, out) in enumerate(zip(trainers, outs)):
            assert p.returncode == 0, f"trainer {i}:\n{out[-4000:]}"
            assert f"TRAINER_OK {i}" in out, out[-2000:]
    finally:
        service.terminate()
        try:
            service.wait(timeout=30)
        except subprocess.TimeoutExpired:
            service.kill()
            service.wait(timeout=10)
