"""Native record loader tests: format round-trip, epoch coverage, sharding,
and C++/numpy fallback parity.
"""

import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.native import (
    NativeRecordLoader,
    RecordFile,
    native_available,
)


@pytest.fixture
def record():
    return RecordFile([
        ("image", (4, 4, 1), np.float32),
        ("label", (), np.int32),
    ])


@pytest.fixture
def record_path(tmp_path, record):
    n = 64
    rng = np.random.RandomState(0)
    arrays = {
        "image": rng.randn(n, 4, 4, 1).astype(np.float32),
        # label encodes the record index so coverage is checkable
        "label": np.arange(n, dtype=np.int32),
    }
    path = str(tmp_path / "data.rec")
    wrote = record.write(path, arrays)
    assert wrote == n
    return path, arrays


class TestRecordFile:
    def test_round_trip(self, record, record_path):
        path, arrays = record_path
        loader = NativeRecordLoader(
            path, record, batch_size=8, shuffle=False,
            shard_index=0, shard_count=1, num_threads=1,
        )
        batch = next(loader)
        assert batch["image"].shape == (8, 4, 4, 1)
        assert batch["label"].shape == (8,)
        # unshuffled single thread: first batch is records 0..7 in order
        np.testing.assert_array_equal(batch["label"], np.arange(8))
        np.testing.assert_allclose(batch["image"], arrays["image"][:8])
        loader.close()


class TestLoader:
    def test_native_library_builds(self):
        # the environment ships g++; the fast path must actually be native
        assert native_available()

    def test_epoch_covers_all_records(self, record, record_path):
        path, _ = record_path
        loader = NativeRecordLoader(
            path, record, batch_size=16, shuffle=True, seed=3,
            shard_index=0, shard_count=1, num_threads=1,
        )
        seen = set()
        for _ in range(4):  # 4 batches of 16 = one epoch of 64
            seen.update(next(loader)["label"].tolist())
        assert seen == set(range(64))
        loader.close()

    def test_sharding_is_disjoint_and_complete(self, record, record_path):
        path, _ = record_path
        seen = set()
        for shard in range(4):
            loader = NativeRecordLoader(
                path, record, batch_size=16, shuffle=False,
                shard_index=shard, shard_count=4, num_threads=1,
            )
            assert loader.num_records == 16
            labels = set(next(loader)["label"].tolist())
            assert labels == {shard + 4 * i for i in range(16)}
            assert not (labels & seen)
            seen |= labels
            loader.close()
        assert seen == set(range(64))

    def test_multithreaded_produces_valid_records(self, record, record_path):
        path, arrays = record_path
        loader = NativeRecordLoader(
            path, record, batch_size=8, shuffle=True, num_threads=4,
            prefetch=8, shard_index=0, shard_count=1,
        )
        for _ in range(20):
            b = next(loader)
            # every record must be internally consistent (image matches label)
            for i in range(8):
                np.testing.assert_allclose(
                    b["image"][i], arrays["image"][b["label"][i]]
                )
        loader.close()

    def test_multithreaded_epoch_has_no_repeats(self, record, record_path):
        """ADVICE r1: producer threads must share ONE epoch stream — with
        num_threads>1 every record appears exactly once per epoch window
        (tf.data DATA contract), not ~Nx with per-thread shuffles."""
        path, _ = record_path
        # num_threads=2/prefetch=1 bound the draw-ahead window: the 8
        # consumed batches come from the first <=11 drawn (2 in-flight + 1
        # ring slot), i.e. <2.75 epochs, so a shared stream can repeat a
        # record at most 3x.  Per-thread duplicate streams would be
        # ~Poisson(2) per record: max 5-6 w.h.p. — the cap discriminates.
        loader = NativeRecordLoader(
            path, record, batch_size=16, shuffle=True, seed=7,
            shard_index=0, shard_count=1, num_threads=2, prefetch=1,
        )
        labels = []
        for _ in range(8):  # 2 epochs of 64 records
            labels.extend(next(loader)["label"].tolist())
        counts = np.bincount(np.asarray(labels), minlength=64)
        assert counts.sum() == 128
        assert counts.max() <= 3, (
            f"record seen {counts.max()}x within 2 epochs — per-thread "
            "duplicate shuffle streams?"
        )
        loader.close()

    def test_numpy_fallback_parity(self, record, record_path, monkeypatch):
        from distributed_tensorflow_tpu.native import loader as loader_mod

        path, arrays = record_path
        monkeypatch.setattr(loader_mod, "_load_library", lambda: None)
        loader = NativeRecordLoader(
            path, record, batch_size=8, shuffle=False,
            shard_index=0, shard_count=1,
        )
        assert loader._handle is None  # fallback active
        b = next(loader)
        np.testing.assert_array_equal(b["label"], np.arange(8))
        np.testing.assert_allclose(b["image"], arrays["image"][:8])

    def test_single_field_batches_do_not_alias(self, tmp_path):
        # regression: single-field records must not alias the loader's
        # reused output buffer across __next__ calls
        rec = RecordFile([("tokens", (8,), np.int32)])
        n = 32
        arrays = {"tokens": np.arange(n * 8, dtype=np.int32).reshape(n, 8)}
        path = str(tmp_path / "tok.rec")
        rec.write(path, arrays)
        loader = NativeRecordLoader(
            path, rec, batch_size=4, shuffle=False,
            shard_index=0, shard_count=1, num_threads=1,
        )
        b1 = next(loader)["tokens"].copy()
        held = next(loader)["tokens"]  # hold WITHOUT copying
        next(loader)
        np.testing.assert_array_equal(
            held, np.arange(32, 64, dtype=np.int32).reshape(4, 8)
        )
        loader.close()

    def test_schema_guard_rejects_stale_or_foreign_files(self, record, tmp_path):
        """A record-format change (e.g. uint8 staging) must fail LOUDLY on
        old files instead of reinterpreting their bytes (review r2)."""
        n = 8
        rng = np.random.RandomState(0)
        arrays = {
            "image": rng.randn(n, 4, 4, 1).astype(np.float32),
            "label": np.arange(n, dtype=np.int32),
        }
        path = str(tmp_path / "ok.rec")
        record.write(path, arrays)
        # (a) same file, different schema (changed record size) -> rejected
        other = RecordFile([("image", (4, 4, 1), np.uint8),
                            ("label", (), np.int32)])
        with pytest.raises(ValueError, match="staging format changed|expects"):
            NativeRecordLoader(path, other, batch_size=2,
                               shard_index=0, shard_count=1)
        # (b) headerless/foreign file -> rejected
        raw = str(tmp_path / "raw.bin")
        with open(raw, "wb") as f:
            f.write(b"\0" * (record.record_bytes * 4))
        with pytest.raises(ValueError, match="not a DTTREC01"):
            NativeRecordLoader(raw, record, batch_size=2,
                               shard_index=0, shard_count=1)
        # (c) append with a mismatched schema -> rejected before writing
        with pytest.raises(ValueError):
            other.write(path, {"image": arrays["image"].astype(np.uint8),
                               "label": arrays["label"]}, append=True)

    def test_missing_file_raises(self, record, tmp_path):
        with pytest.raises(FileNotFoundError):
            NativeRecordLoader(
                str(tmp_path / "nope.rec"), record, batch_size=4,
                shard_index=0, shard_count=1,
            )


class TestUint8Staging:
    def test_quantize_roundtrip(self):
        from distributed_tensorflow_tpu.models.resnet import (
            IMG_OFFSET,
            IMG_SCALE,
            quantize_images,
        )

        rng = np.random.RandomState(0)
        x = rng.randn(8, 4, 4, 3).astype(np.float32)
        q = quantize_images({"image": x, "label": np.zeros(8)})["image"]
        assert q.dtype == np.uint8
        back = (q.astype(np.float32) - IMG_OFFSET) / IMG_SCALE
        # quantization error bounded by half a step within the u8 range
        clipped = np.clip(x, -IMG_OFFSET / IMG_SCALE, (255 - IMG_OFFSET) / IMG_SCALE)
        np.testing.assert_allclose(back, clipped, atol=0.5 / IMG_SCALE + 1e-6)

    def test_resnet_schema_is_uint8_and_trains(self, tmp_path, mesh_dp):
        """Stage→load→train through the uint8 path: records are 1/4 size,
        and from_record dequantizes on device inside the compiled step."""
        import jax
        from distributed_tensorflow_tpu.data.pipeline import (
            make_global_batches,
        )
        from distributed_tensorflow_tpu.data.records import (
            record_data_fn,
            record_path,
            record_schema,
            stage_synthetic_to_records,
        )
        from distributed_tensorflow_tpu.models import get_workload
        from distributed_tensorflow_tpu.train_lib import build_state_and_step

        wl = get_workload("resnet50", batch_size=8, num_classes=4,
                          image_size=32, stage_sizes=(1, 1, 1, 1))
        schema = record_schema(wl)
        img_field = dict((n, d) for n, _, d in schema.fields)["image"]
        assert img_field == np.uint8
        path = record_path(str(tmp_path), "resnet50")
        stage_synthetic_to_records(wl, path, 64)
        assert os.path.getsize(path) == schema.file_size(64)

        state, _, train_step, batch_sh = build_state_and_step(
            wl, mesh_dp, total_steps=4,
        )
        data = make_global_batches(
            record_data_fn(path, wl, num_threads=1)(8),
            batch_sh[wl.example_key],
        )
        rng = jax.random.key(0)
        losses = []
        for i, batch in zip(range(4), data):
            assert batch["image"].dtype == np.uint8  # staged form on device
            state, m = train_step(state, batch, jax.random.fold_in(rng, i))
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all()


class TestRecordTrainingPath:
    def test_stage_and_train_end_to_end(self, tmp_path):
        """Full native-input training: stage synthetic mnist records, train
        via train.py's --data_dir path, loss finite and steps complete."""
        from distributed_tensorflow_tpu.data.records import (
            record_path,
            record_schema,
            stage_synthetic_to_records,
        )
        from distributed_tensorflow_tpu.models import get_workload
        from distributed_tensorflow_tpu.train_lib import TrainArgs, run

        wl = get_workload("mnist", batch_size=32)
        path = record_path(str(tmp_path), "mnist")
        n = stage_synthetic_to_records(wl, path, 256)
        assert n == 256
        schema = record_schema(wl)
        assert os.path.getsize(path) == schema.file_size(256)

        result = run(TrainArgs(
            model="mnist", steps=10, batch_size=32, log_every=5,
            data_dir=str(tmp_path),
        ))
        assert result["final_step"] == 10
        assert np.isfinite(result["loss"])


class TestRecordSetLoader:
    """Multi-file filesets with FILE/DATA/AUTO auto-shard (VERDICT r3 #4:
    the reference's 1024-shard input layout)."""

    @pytest.fixture
    def fileset(self, tmp_path, record):
        # 4 files x 16 records, label = global record id (file-major)
        paths = []
        rng = np.random.RandomState(1)
        for f in range(4):
            arrays = {
                "image": rng.randn(16, 4, 4, 1).astype(np.float32),
                "label": (np.arange(16) + 100 * f).astype(np.int32),
            }
            p = str(tmp_path / f"data-{f:05d}-of-00004.rec")
            record.write(p, arrays)
            paths.append(p)
        return paths

    def _labels_of_shard(self, record, paths, policy, s, n, draws=64):
        from distributed_tensorflow_tpu.native import RecordSetLoader

        ld = RecordSetLoader(
            paths, record, batch_size=4, shuffle=False, policy=policy,
            shard_index=s, shard_count=n, num_threads=1,
        )
        seen = set()
        for _ in range(draws):
            seen.update(int(x) for x in next(ld)["label"])
        ld.close()
        return seen, ld.policy

    def test_file_policy_assigns_whole_files(self, record, fileset):
        seen0, pol = self._labels_of_shard(record, fileset, "file", 0, 2)
        seen1, _ = self._labels_of_shard(record, fileset, "file", 1, 2)
        assert pol == "file"
        # shard 0 -> files 0, 2; shard 1 -> files 1, 3 (whole files)
        want0 = {i + 100 * f for f in (0, 2) for i in range(16)}
        want1 = {i + 100 * f for f in (1, 3) for i in range(16)}
        assert seen0 == want0
        assert seen1 == want1

    def test_data_policy_stripes_globally_disjoint_complete(
            self, record, fileset):
        seen0, pol = self._labels_of_shard(record, fileset, "data", 0, 2)
        seen1, _ = self._labels_of_shard(record, fileset, "data", 1, 2)
        assert pol == "data"
        every = {i + 100 * f for f in range(4) for i in range(16)}
        assert seen0 | seen1 == every
        assert not (seen0 & seen1)
        # exact tf.data DATA semantics: global record j -> shard j % 2,
        # global order is file-major concatenation
        glob = [i + 100 * f for f in range(4) for i in range(16)]
        assert seen0 == set(glob[0::2])
        assert seen1 == set(glob[1::2])

    def test_auto_picks_file_then_falls_back_to_data(self, record, fileset):
        _, pol = self._labels_of_shard(record, fileset, "auto", 0, 2)
        assert pol == "file"  # 4 files >= 2 shards
        _, pol = self._labels_of_shard(record, fileset, "auto", 0, 8)
        assert pol == "data"  # 4 files < 8 shards

    def test_file_policy_rejects_starved_shard(self, record, fileset):
        from distributed_tensorflow_tpu.native import RecordSetLoader

        with pytest.raises(FileNotFoundError):
            RecordSetLoader(
                fileset, record, batch_size=4, policy="file",
                shard_index=5, shard_count=8, num_threads=1,
            )

    def test_stage_synthetic_writes_fileset_and_resolves(self, tmp_path):
        from distributed_tensorflow_tpu.data.records import (
            record_paths,
            record_schema,
            stage_synthetic_to_records,
        )
        from distributed_tensorflow_tpu.models import get_workload

        wl = get_workload("mnist", batch_size=16)
        base = str(tmp_path / "mnist.rec")
        n = stage_synthetic_to_records(wl, base, 40, chunk=16, num_files=4)
        assert n == 40
        paths = record_paths(str(tmp_path), "mnist")
        assert len(paths) == 4
        schema = record_schema(wl)
        total = 0
        for p in paths:
            payload = os.path.getsize(p) - 16
            assert payload % schema.record_bytes == 0
            total += payload // schema.record_bytes
        assert total == 40

    def test_train_end_to_end_from_fileset(self, tmp_path):
        from distributed_tensorflow_tpu.data.records import (
            stage_synthetic_to_records,
        )
        from distributed_tensorflow_tpu.models import get_workload
        from distributed_tensorflow_tpu.train_lib import TrainArgs, run

        wl = get_workload("mnist", batch_size=16)
        stage_synthetic_to_records(
            wl, str(tmp_path / "mnist.rec"), 64, chunk=16, num_files=4)
        res = run(TrainArgs(
            model="mnist", steps=6, batch_size=16, log_every=2,
            data_dir=str(tmp_path), auto_shard_policy="auto",
        ))
        assert res["final_step"] == 6
        assert np.isfinite(res["loss"])

    def test_record_paths_rejects_mixed_generations(self, tmp_path, record):
        from distributed_tensorflow_tpu.data.records import record_paths

        arrays = {
            "image": np.zeros((4, 4, 4, 1), np.float32),
            "label": np.arange(4, dtype=np.int32),
        }
        for name in ("d-00000-of-00004.rec", "d-00001-of-00004.rec",
                     "d-00002-of-00004.rec", "d-00003-of-00004.rec",
                     "d-00000-of-00002.rec"):  # stale older generation
            record.write(str(tmp_path / name), arrays)
        with pytest.raises(ValueError, match="mixes generations"):
            record_paths(str(tmp_path), "d")
        os.unlink(str(tmp_path / "d-00000-of-00002.rec"))
        assert len(record_paths(str(tmp_path), "d")) == 4
