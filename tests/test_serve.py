"""serve/ subsystem tests: KV-cache decode parity, dynamic batcher
behavior (full-batch flush, timeout flush, rejection, out-of-order
completion), engine restore/classify paths, checkpoint teardown surface,
and the ServeMonitorHook export.

All run on the forced 8-CPU-device platform from conftest.py; the sharded
parity test uses the data=4 x tensor=2 mesh — the ``--tensor=2`` acceptance
configuration.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.serve import (
    DynamicBatcher,
    ServeEngine,
    ServeOverloadedError,
    pad_rows,
)


# ---------------------------------------------------------------------------
# DynamicBatcher
# ---------------------------------------------------------------------------

class _Recorder:
    """run_batch stub that records every dispatched batch."""

    def __init__(self, delay_s=0.0, fail=False):
        self.batches = []
        self.delay_s = delay_s
        self.fail = fail
        self.lock = threading.Lock()

    def __call__(self, payloads):
        with self.lock:
            self.batches.append(list(payloads))
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise ValueError("engine exploded")
        return [p * 10 for p in payloads]


class TestDynamicBatcher:
    def test_full_batch_flushes_immediately(self):
        rec = _Recorder()
        # Long timeout: only the full-bucket rule can flush this fast.
        with DynamicBatcher(rec, max_batch_size=4,
                            batch_timeout_ms=10_000) as b:
            futs = [b.submit(i) for i in range(4)]
            results = [f.result(timeout=5) for f in futs]
        assert results == [0, 10, 20, 30]
        assert [len(x) for x in rec.batches] == [4]

    def test_timeout_flushes_partial_batch(self):
        rec = _Recorder()
        with DynamicBatcher(rec, max_batch_size=8,
                            batch_timeout_ms=30) as b:
            t0 = time.monotonic()
            f = b.submit(7)
            assert f.result(timeout=5) == 70
            waited = time.monotonic() - t0
        # Flushed by the timeout (not full, not close()).
        assert rec.batches == [[7]]
        assert waited >= 0.025

    def test_rejection_under_overload(self):
        release = threading.Event()

        def blocked(payloads):
            release.wait(10)
            return payloads

        b = DynamicBatcher(blocked, max_batch_size=2, batch_timeout_ms=1,
                           max_queue_size=3)
        try:
            for i in range(2):
                b.submit(i)
            # Give the scheduler time to move the first batch in-flight,
            # then fill the queue to its bound.
            time.sleep(0.05)
            for i in range(3):
                b.submit(i)
            with pytest.raises(ServeOverloadedError):
                b.submit(99)
            assert b.stats()["rejected"] == 1.0
        finally:
            release.set()
            b.close()

    def test_out_of_order_completion_full_bucket_first(self):
        order = []
        lock = threading.Lock()

        def run(payloads):
            with lock:
                order.append(list(payloads))
            return payloads

        # Bucket by parity.  Submit ONE odd request first, then a FULL even
        # bucket: the full bucket must flush ahead of the older partial one.
        b = DynamicBatcher(run, max_batch_size=3, batch_timeout_ms=200,
                           bucket_fn=lambda p: p % 2)
        try:
            f_odd = b.submit(1)
            time.sleep(0.02)
            evens = [b.submit(p) for p in (0, 2, 4)]
            assert [f.result(timeout=5) for f in evens] == [0, 2, 4]
            assert f_odd.result(timeout=5) == 1
        finally:
            b.close()
        assert order[0] == [0, 2, 4], order  # younger full bucket won
        assert order[1] == [1], order

    def test_buckets_never_mix(self):
        rec = _Recorder()
        with DynamicBatcher(rec, max_batch_size=8, batch_timeout_ms=10,
                            bucket_fn=lambda p: p % 2) as b:
            futs = [b.submit(i) for i in range(6)]
            for f in futs:
                f.result(timeout=5)
        for batch in rec.batches:
            assert len({p % 2 for p in batch}) == 1, rec.batches

    def test_concurrent_clients_get_their_own_results(self):
        rec = _Recorder()
        results = {}
        lock = threading.Lock()
        with DynamicBatcher(rec, max_batch_size=4, batch_timeout_ms=2) as b:
            def client(base):
                for i in range(base, base + 25):
                    r = b.submit(i).result(timeout=10)
                    with lock:
                        results[i] = r

            threads = [threading.Thread(target=client, args=(c * 100,))
                       for c in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert len(results) == 100
        assert all(v == k * 10 for k, v in results.items())

    def test_engine_error_propagates_to_futures(self):
        rec = _Recorder(fail=True)
        with DynamicBatcher(rec, max_batch_size=2, batch_timeout_ms=1) as b:
            f1, f2 = b.submit(1), b.submit(2)
            with pytest.raises(ValueError, match="engine exploded"):
                f1.result(timeout=5)
            with pytest.raises(ValueError):
                f2.result(timeout=5)
            assert b.stats()["failed"] == 2.0

    def test_close_fails_pending_and_rejects_new(self):
        release = threading.Event()

        def blocked(payloads):
            release.wait(10)
            return payloads

        b = DynamicBatcher(blocked, max_batch_size=1, batch_timeout_ms=1,
                           max_queue_size=8)
        inflight = b.submit(0)
        time.sleep(0.05)  # scheduler now blocked inside run_batch
        pending = b.submit(1)
        # Worker still blocked: request 1 is never dispatched, so close()
        # must fail its future rather than leave the caller hanging.
        b.close(timeout=0.2)
        b.close()  # idempotent
        with pytest.raises(RuntimeError):
            pending.result(timeout=5)
        with pytest.raises(RuntimeError):
            b.submit(2)
        release.set()  # the in-flight batch still completes normally
        assert inflight.result(timeout=5) == 0

    def test_stats_counters(self):
        rec = _Recorder()
        with DynamicBatcher(rec, max_batch_size=2, batch_timeout_ms=2) as b:
            futs = [b.submit(i) for i in range(6)]
            for f in futs:
                f.result(timeout=5)
            s = b.stats()
        assert s["submitted"] == 6.0
        assert s["completed"] == 6.0
        assert s["queue_depth"] == 0.0
        assert s["batches"] >= 3.0
        assert 1.0 <= s["avg_batch_occupancy"] <= 2.0
        assert s["p50_latency_ms"] >= 0.0
        assert s["p99_latency_ms"] >= s["p50_latency_ms"]


# ---------------------------------------------------------------------------
# pad_rows
# ---------------------------------------------------------------------------

class TestPadRows:
    def test_pads_by_repeating_last_row(self):
        a = np.arange(6, dtype=np.int32).reshape(3, 2)
        out = pad_rows(a, 5)
        assert out.shape == (5, 2)
        np.testing.assert_array_equal(out[3], a[-1])
        np.testing.assert_array_equal(out[4], a[-1])

    def test_noop_and_overflow(self):
        a = np.zeros((4, 2))
        assert pad_rows(a, 4) is a
        with pytest.raises(ValueError):
            pad_rows(a, 2)


# ---------------------------------------------------------------------------
# KV-cache decode parity (satellite c)
# ---------------------------------------------------------------------------

def _tiny_gpt2(**kw):
    from distributed_tensorflow_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config.tiny(dtype=jnp.float32, **kw)
    return GPT2(cfg), cfg


def _fresh_cache(model, B, T):
    """Zeroed decode cache for B rows of up to T tokens.  ``init`` returns
    POST-call variables (cache_index/position already advanced past the init
    input), so zero the whole tree — what the engine's ``init_cache`` does
    via eval_shape."""
    vs = jax.eval_shape(lambda: model.init(
        jax.random.key(0), jnp.zeros((B, T), jnp.int32), decode=True))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), vs["cache"])


def _incremental_logits(model, params, cache, tokens, prefill):
    """Prefill ``prefill`` tokens, then decode one token at a time;
    concatenated logits over the whole sequence."""
    @jax.jit
    def step(params, cache, tok):
        logits, vs = model.apply(
            {"params": params, "cache": cache}, tok,
            decode=True, mutable=["cache"])
        return logits, vs["cache"]

    T = tokens.shape[1]
    logits, cache = step(params, cache, tokens[:, :prefill])
    outs = [logits]
    for i in range(prefill, T):
        logits, cache = step(params, cache, tokens[:, i:i + 1])
        outs.append(logits)
    return jnp.concatenate(outs, axis=1)


class TestDecodeParity:
    def test_incremental_matches_full_forward(self):
        model, cfg = _tiny_gpt2()
        B, T = 2, 10
        tokens = jax.random.randint(
            jax.random.key(1), (B, T), 0, cfg.vocab_size)
        params = model.init(jax.random.key(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        cache = _fresh_cache(model, B, T)
        inc = _incremental_logits(model, params, cache, tokens, prefill=4)
        np.testing.assert_allclose(
            np.asarray(inc), np.asarray(full), rtol=1e-4, atol=1e-4)

    def test_prefill_only_matches_full_forward(self):
        model, cfg = _tiny_gpt2()
        B, T = 2, 8
        tokens = jax.random.randint(
            jax.random.key(2), (B, T), 0, cfg.vocab_size)
        params = model.init(jax.random.key(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)
        cache = _fresh_cache(model, B, T)
        pre, _ = model.apply(
            {"params": params, "cache": cache}, tokens,
            decode=True, mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(pre), np.asarray(full), rtol=1e-4, atol=1e-4)

    def test_parity_under_tensor_parallel_mesh(self, mesh_2d):
        """The --tensor=2 acceptance case: params sharded by gpt2_rules,
        cache by gpt2_cache_rules, on the data=4 x tensor=2 CPU mesh."""
        from distributed_tensorflow_tpu.models.gpt2 import (
            gpt2_cache_rules,
            gpt2_rules,
        )
        from distributed_tensorflow_tpu.parallel.sharding import (
            apply_shardings,
            batch_sharding,
        )

        model, cfg = _tiny_gpt2()
        B, T = 4, 12
        tokens = np.asarray(jax.random.randint(
            jax.random.key(3), (B, T), 0, cfg.vocab_size))
        params = model.init(jax.random.key(0), tokens)["params"]
        params = apply_shardings(
            params, gpt2_rules().shardings_for(mesh_2d, params))
        tok_dev = jax.device_put(tokens, batch_sharding(mesh_2d))
        full = jax.jit(lambda p, t: model.apply({"params": p}, t))(
            params, tok_dev)

        cache_shapes = jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((B, T), jnp.int32),
            decode=True))["cache"]
        cache = jax.jit(
            lambda: jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes),
            out_shardings=gpt2_cache_rules().shardings_for(
                mesh_2d, cache_shapes),
        )()
        inc = _incremental_logits(model, params, cache, tok_dev, prefill=5)
        np.testing.assert_allclose(
            np.asarray(inc), np.asarray(full), rtol=2e-4, atol=2e-4)

    def test_cache_rules_shard_heads_over_tensor(self, mesh_2d):
        from distributed_tensorflow_tpu.models.gpt2 import gpt2_cache_rules

        model, cfg = _tiny_gpt2()
        shapes = jax.eval_shape(lambda: model.init(
            jax.random.key(0), jnp.zeros((2, 8), jnp.int32),
            decode=True))["cache"]
        sh = gpt2_cache_rules().shardings_for(mesh_2d, shapes)
        flat = {"/".join(str(k.key) for k in path): s
                for path, s in jax.tree_util.tree_flatten_with_path(sh)[0]}
        key_spec = next(v.spec for k, v in flat.items() if "cached_key" in k)
        assert "tensor" in tuple(key_spec)

    def test_decode_rejects_pipeline_parallel(self, devices8):
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh
        from distributed_tensorflow_tpu.models.gpt2 import GPT2

        mesh = build_mesh(MeshConfig(data=4, pipe=2), devices8)
        _, cfg = _tiny_gpt2()
        model = GPT2(cfg, mesh=mesh)
        with pytest.raises(ValueError, match="pipe"):
            model.init(jax.random.key(0), jnp.zeros((4, 8), jnp.int32),
                       decode=True)


# ---------------------------------------------------------------------------
# ServeEngine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt2_engine(request):
    mesh_dp = request.getfixturevalue("mesh_dp")
    eng = ServeEngine("gpt2", mesh=mesh_dp, preset="tiny")
    yield eng
    eng.close()


class TestServeEngine:
    def test_pipe_mesh_rejected_at_construction(self, devices8):
        """A decode-capable model on a pipeline-split mesh must fail at
        ServeEngine CONSTRUCTION, naming the mesh axis — not deep inside
        the first decode apply after params already materialized."""
        from distributed_tensorflow_tpu.cluster import MeshConfig, build_mesh

        mesh = build_mesh(MeshConfig(data=4, pipe=2), devices8)
        with pytest.raises(ValueError,
                           match=r"'pipe' axis of size 2.*pipeline"):
            ServeEngine("gpt2", mesh=mesh, preset="tiny")

    def test_generate_shape_dtype_determinism(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        prompts = np.asarray(
            jax.random.randint(jax.random.key(4), (8, 6), 0, vocab))
        a = gpt2_engine.generate(prompts, max_new_tokens=5)
        b = gpt2_engine.generate(prompts, max_new_tokens=5)
        assert a.shape == (8, 5) and a.dtype == np.int32
        np.testing.assert_array_equal(a, b)  # greedy decode is deterministic
        assert (a >= 0).all() and (a < vocab).all()

    def test_generate_matches_full_forward_argmax(self, gpt2_engine):
        """The first generated token must equal argmax of the plain full
        forward — ties the serving path to the training-time model."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompts = np.asarray(
            jax.random.randint(jax.random.key(5), (8, 7), 0, vocab))
        gen = gpt2_engine.generate(prompts, max_new_tokens=1)
        logits = gpt2_engine.module.apply(
            {"params": gpt2_engine.params}, jnp.asarray(prompts))
        expect = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))
        np.testing.assert_array_equal(gen[:, 0], expect)

    def test_generate_batch_pads_and_scatters(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(0)
        # 3 ragged prompts of two lengths; batch dim padded internally.
        prompts = [rng.integers(0, vocab, size=(n,), dtype=np.int32)
                   for n in (6, 4, 6)]
        outs = gpt2_engine.generate_batch(prompts, max_new_tokens=3)
        assert [o.shape for o in outs] == [(3,)] * 3
        # Same-length prompts must agree with a direct padded generate.
        direct = gpt2_engine.generate(
            pad_rows(np.stack([prompts[0], prompts[2]]),
                     gpt2_engine.bucket_rows(2)), 3)
        np.testing.assert_array_equal(outs[0], direct[0])
        np.testing.assert_array_equal(outs[2], direct[1])

    def test_generate_rejects_overlong(self, gpt2_engine):
        n_pos = gpt2_engine.module.cfg.n_positions
        with pytest.raises(ValueError, match="n_positions"):
            gpt2_engine.generate(
                np.zeros((8, n_pos), np.int32), max_new_tokens=1)

    def test_bucket_rows_pow2_multiple_of_dp(self, gpt2_engine):
        dp = gpt2_engine.data_parallelism
        assert dp == 8
        assert gpt2_engine.bucket_rows(1) == 8
        assert gpt2_engine.bucket_rows(8) == 8
        assert gpt2_engine.bucket_rows(9) == 16

    def test_classify_mnist(self, mesh_dp):
        with ServeEngine("mnist", mesh=mesh_dp, batch_size=32) as eng:
            batch = next(eng.workload.data_fn(16))
            preds = eng.classify_batch(
                [{"image": batch["image"][i]} for i in range(10)])
        assert len(preds) == 10
        assert all(0 <= p < 10 for p in preds)

    def test_restore_roundtrip(self, mesh_dp, tmp_path):
        """Train-side save -> serve-side restore_params -> identical params
        and a working generate — the checkpoint_dir acceptance path."""
        from distributed_tensorflow_tpu.checkpoint import CheckpointManager
        from distributed_tensorflow_tpu.models import get_workload
        from distributed_tensorflow_tpu.train_lib import build_state_and_step

        ckdir = str(tmp_path / "ck")
        wl = get_workload("gpt2", mesh=mesh_dp, preset="tiny")
        state, _, _, _ = build_state_and_step(wl, mesh_dp, total_steps=1)
        with CheckpointManager(ckdir, async_save=False) as m:
            assert m.save(0, state, force=True)
        saved_params = jax.device_get(state.params)

        with ServeEngine("gpt2", mesh=mesh_dp, checkpoint_dir=ckdir,
                         preset="tiny") as eng:
            assert eng.restored_step == 0
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)),
                jax.device_get(eng.params), saved_params)
            out = eng.generate(np.zeros((8, 4), np.int32), 2)
        assert out.shape == (8, 2)

    def test_missing_checkpoint_falls_back_to_fresh_init(
            self, mesh_dp, tmp_path):
        with ServeEngine("gpt2", mesh=mesh_dp, preset="tiny",
                         checkpoint_dir=str(tmp_path / "empty")) as eng:
            assert eng.restored_step is None
            assert eng.generate(np.zeros((8, 4), np.int32), 1).shape == (8, 1)


# ---------------------------------------------------------------------------
# EOS early exit in the fixed-batch path
# ---------------------------------------------------------------------------

class TestEosEarlyExit:
    def _reference(self, eng, prompts, n):
        """Greedy stream with no eos — the early-exit runs must be a
        prefix of this (same jitted program, deterministic on CPU)."""
        return eng.generate(prompts, n)

    def test_stops_before_horizon_when_all_rows_hit_eos(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.asarray(jax.random.randint(
            jax.random.key(8), (1, 6), 0, vocab))
        prompts = np.repeat(prompt, 8, axis=0)  # identical rows: one eos hit
        ref = self._reference(gpt2_engine, prompts, 12)
        eos = int(ref[0, 3])
        out = gpt2_engine.generate(prompts, 12, eos_token=eos,
                                   eos_check_every=1)
        assert out.shape[1] == 4  # stopped at the eos, not the horizon
        np.testing.assert_array_equal(out, ref[:, :4])

    def test_check_cadence_bounds_overshoot(self, gpt2_engine):
        """With eos_check_every=N the loop may overshoot by < N steps but
        still stops well short of the horizon; emitted tokens stay a prefix
        of the unrestricted stream."""
        vocab = gpt2_engine.module.cfg.vocab_size
        prompt = np.asarray(jax.random.randint(
            jax.random.key(8), (1, 6), 0, vocab))
        prompts = np.repeat(prompt, 8, axis=0)
        ref = self._reference(gpt2_engine, prompts, 16)
        eos = int(ref[0, 3])
        out = gpt2_engine.generate(prompts, 16, eos_token=eos,
                                   eos_check_every=4)
        assert 4 <= out.shape[1] < 4 + 4  # eos at 4, next check within 4
        np.testing.assert_array_equal(out, ref[:, : out.shape[1]])

    def test_no_eos_decodes_full_horizon(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        prompts = np.asarray(jax.random.randint(
            jax.random.key(9), (8, 5), 0, vocab))
        ref = self._reference(gpt2_engine, prompts, 6)
        out = gpt2_engine.generate(prompts, 6, eos_token=vocab - 1
                                   if (ref != vocab - 1).all() else None,
                                   eos_check_every=1)
        np.testing.assert_array_equal(out, ref)

    def test_generate_batch_trims_each_row_at_its_eos(self, gpt2_engine):
        vocab = gpt2_engine.module.cfg.vocab_size
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, vocab, size=(5,), dtype=np.int32)
                   for _ in range(3)]
        ref = gpt2_engine.generate_batch(prompts, 8)
        eos = int(ref[1][2])  # row 1 should cut at index 2 (inclusive)
        outs = gpt2_engine.generate_batch(prompts, 8, eos_token=eos)
        assert len(outs[1]) <= 3 and outs[1][-1] == eos
        for r, o in zip(ref, outs):
            np.testing.assert_array_equal(o, r[: len(o)])


# ---------------------------------------------------------------------------
# CheckpointManager teardown surface (satellite b)
# ---------------------------------------------------------------------------

class TestCheckpointManagerClose:
    def test_close_idempotent_and_context_manager(self, tmp_path):
        from distributed_tensorflow_tpu.checkpoint import CheckpointManager

        m = CheckpointManager(str(tmp_path / "a"))
        assert not m.closed
        m.close()
        assert m.closed
        m.close()  # second close is a no-op
        m.wait_until_finished()  # safe after close

        with CheckpointManager(str(tmp_path / "b")) as m2:
            assert not m2.closed
        assert m2.closed

    def test_restore_params_without_template(self, tmp_path):
        import optax

        from distributed_tensorflow_tpu.checkpoint import CheckpointManager
        from distributed_tensorflow_tpu.training import TrainState

        params = {"w": jnp.arange(4.0), "b": jnp.ones((2,))}
        state = TrainState.create(
            apply_fn=lambda *a, **k: None, params=params,
            tx=optax.sgd(0.1), model_state={})
        d = str(tmp_path / "ck")
        with CheckpointManager(d, async_save=False) as m:
            m.save(3, state, force=True)
        with CheckpointManager(d) as m:
            got, model_state = m.restore_params()
        assert model_state == {}
        np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4.0))

    def test_restore_params_missing_dir_raises(self, tmp_path):
        from distributed_tensorflow_tpu.checkpoint import CheckpointManager

        with CheckpointManager(str(tmp_path / "none")) as m:
            with pytest.raises(FileNotFoundError):
                m.restore_params()


# ---------------------------------------------------------------------------
# ServeMonitorHook
# ---------------------------------------------------------------------------

class TestServeMonitorHook:
    def test_exports_batcher_counters(self, caplog):
        import logging

        from distributed_tensorflow_tpu.obs import ServeMonitorHook

        rec = _Recorder()
        with DynamicBatcher(rec, max_batch_size=2, batch_timeout_ms=2) as b:
            hook = ServeMonitorHook(b, every_steps=1)
            futs = [b.submit(i) for i in range(4)]
            for f in futs:
                f.result(timeout=5)
            m = hook.metrics()
            with caplog.at_level(logging.INFO,
                                 logger="distributed_tensorflow_tpu.obs.serve"):
                logged = hook.log(4)
        for key in ("serve_queue_depth", "serve_completed",
                    "serve_avg_batch_occupancy", "serve_p50_latency_ms",
                    "serve_p99_latency_ms", "serve_rejected"):
            assert key in m, m
        assert logged["serve_completed"] == 4.0
        assert any("serve @ 4" in r.message for r in caplog.records)

    def test_tolerates_source_without_stats(self):
        from distributed_tensorflow_tpu.obs import ServeMonitorHook

        hook = ServeMonitorHook(object())
        assert hook.metrics() == {}
        assert hook.log(1) is None
