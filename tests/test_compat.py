"""Tests: environment cluster resolvers + TF1 API-compatibility shims."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from distributed_tensorflow_tpu.cluster import (
    GCEClusterResolver,
    KubernetesClusterResolver,
    SlurmClusterResolver,
    resolve,
)
from distributed_tensorflow_tpu.cluster.resolver import _expand_slurm_nodelist
from distributed_tensorflow_tpu.compat import (
    NcclAllReduce,
    SyncReplicasOptimizer,
    replica_device_setter,
)


class TestSlurmResolver:
    def test_nodelist_expansion(self):
        assert _expand_slurm_nodelist("node[1-3]") == ["node1", "node2", "node3"]
        assert _expand_slurm_nodelist("n[01-03,07]") == [
            "n01", "n02", "n03", "n07",
        ]
        assert _expand_slurm_nodelist("a,b[2],c") == ["a", "b2", "c"]
        assert _expand_slurm_nodelist("") == []

    def test_cluster_spec_from_env(self):
        env = {"SLURM_PROCID": "1", "SLURM_NTASKS": "4",
               "SLURM_NODELIST": "tpu[0-3]"}
        r = SlurmClusterResolver(environ=env)
        spec = r.cluster_spec()
        assert r.task_id == 1 and r.task_type == "worker"
        assert spec.num_processes() == 4
        assert "tpu0:8888" in spec.job_tasks("worker")[0]

    def test_resolve_priority(self, monkeypatch):
        monkeypatch.delenv("TF_CONFIG", raising=False)
        monkeypatch.setenv("SLURM_PROCID", "0")
        monkeypatch.setenv("SLURM_NTASKS", "2")
        monkeypatch.setenv("SLURM_NODELIST", "h[0-1]")
        assert isinstance(resolve(), SlurmClusterResolver)
        # TF_CONFIG wins over Slurm
        monkeypatch.setenv(
            "TF_CONFIG",
            '{"cluster": {"worker": ["a:1"]}, '
            '"task": {"type": "worker", "index": 0}}',
        )
        from distributed_tensorflow_tpu.cluster import TFConfigClusterResolver

        assert isinstance(resolve(), TFConfigClusterResolver)


class TestK8sGceResolvers:
    def test_k8s(self):
        r = KubernetesClusterResolver(environ={
            "DTT_K8S_WORKER_HOSTS": "pod-0:9000, pod-1:9000",
            "DTT_K8S_POD_INDEX": "1",
        })
        assert r.task_id == 1
        assert r.cluster_spec().job_tasks("worker") == [
            "pod-0:9000", "pod-1:9000",
        ]

    def test_gce(self):
        r = GCEClusterResolver(environ={
            "DTT_GCE_INSTANCES": "inst-0:8888,inst-1:8888",
            "DTT_GCE_INDEX": "0",
        })
        assert r.cluster_spec().num_processes() == 2


class TestSyncReplicasOptimizer:
    def test_aggregates_k_microbatch_grads(self):
        # k updates with SyncReplicas(k) == 1 update with mean of k grads
        k = 4
        sync = SyncReplicasOptimizer(optax.sgd(0.1), replicas_to_aggregate=k)
        tx = sync.as_gradient_transformation()
        params = {"w": jnp.ones((3,))}
        state = tx.init(params)
        grads = [{"w": jnp.full((3,), float(i + 1))} for i in range(k)]
        p = params
        for g in grads:
            updates, state = tx.update(g, state, p)
            p = optax.apply_updates(p, updates)
        expected = 1.0 - 0.1 * np.mean([1, 2, 3, 4])
        np.testing.assert_allclose(np.asarray(p["w"]), expected, rtol=1e-6)

    def test_graph_mode_api_raises(self):
        sync = SyncReplicasOptimizer(optax.sgd(0.1), 2)
        with pytest.raises(NotImplementedError):
            sync.apply_gradients([])


class TestDeviceSetterAndCrossDeviceOps:
    def test_replica_device_setter_noop(self):
        fn = replica_device_setter(ps_tasks=3)
        assert fn() == ""

    def test_nccl_allreduce_reduces(self):
        ops = NcclAllReduce(num_packs=2)
        out = ops.reduce("MEAN", jnp.arange(4.0))
        assert float(out) == pytest.approx(1.5)
        assert "ici" in ops.algorithm
