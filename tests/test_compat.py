"""Tests: environment cluster resolvers + TF1 API-compatibility shims."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from distributed_tensorflow_tpu.cluster import (
    GCEClusterResolver,
    KubernetesClusterResolver,
    SlurmClusterResolver,
    resolve,
)
from distributed_tensorflow_tpu.cluster.resolver import _expand_slurm_nodelist
from distributed_tensorflow_tpu.compat import (
    NcclAllReduce,
    SyncReplicasOptimizer,
    replica_device_setter,
)


class TestSlurmResolver:
    def test_nodelist_expansion(self):
        assert _expand_slurm_nodelist("node[1-3]") == ["node1", "node2", "node3"]
        assert _expand_slurm_nodelist("n[01-03,07]") == [
            "n01", "n02", "n03", "n07",
        ]
        assert _expand_slurm_nodelist("a,b[2],c") == ["a", "b2", "c"]
        assert _expand_slurm_nodelist("") == []

    def test_cluster_spec_from_env(self):
        env = {"SLURM_PROCID": "1", "SLURM_NTASKS": "4",
               "SLURM_NODELIST": "tpu[0-3]"}
        r = SlurmClusterResolver(environ=env)
        spec = r.cluster_spec()
        assert r.task_id == 1 and r.task_type == "worker"
        assert spec.num_processes() == 4
        assert "tpu0:8888" in spec.job_tasks("worker")[0]

    def test_resolve_priority(self, monkeypatch):
        monkeypatch.delenv("TF_CONFIG", raising=False)
        monkeypatch.setenv("SLURM_PROCID", "0")
        monkeypatch.setenv("SLURM_NTASKS", "2")
        monkeypatch.setenv("SLURM_NODELIST", "h[0-1]")
        assert isinstance(resolve(), SlurmClusterResolver)
        # TF_CONFIG wins over Slurm
        monkeypatch.setenv(
            "TF_CONFIG",
            '{"cluster": {"worker": ["a:1"]}, '
            '"task": {"type": "worker", "index": 0}}',
        )
        from distributed_tensorflow_tpu.cluster import TFConfigClusterResolver

        assert isinstance(resolve(), TFConfigClusterResolver)


class TestK8sGceResolvers:
    def test_k8s(self):
        r = KubernetesClusterResolver(environ={
            "DTT_K8S_WORKER_HOSTS": "pod-0:9000, pod-1:9000",
            "DTT_K8S_POD_INDEX": "1",
        })
        assert r.task_id == 1
        assert r.cluster_spec().job_tasks("worker") == [
            "pod-0:9000", "pod-1:9000",
        ]

    def test_gce(self):
        r = GCEClusterResolver(environ={
            "DTT_GCE_INSTANCES": "inst-0:8888,inst-1:8888",
            "DTT_GCE_INDEX": "0",
        })
        assert r.cluster_spec().num_processes() == 2


class TestSyncReplicasOptimizer:
    def test_aggregates_k_microbatch_grads(self):
        # k updates with SyncReplicas(k) == 1 update with mean of k grads
        k = 4
        sync = SyncReplicasOptimizer(optax.sgd(0.1), replicas_to_aggregate=k)
        tx = sync.as_gradient_transformation()
        params = {"w": jnp.ones((3,))}
        state = tx.init(params)
        grads = [{"w": jnp.full((3,), float(i + 1))} for i in range(k)]
        p = params
        for g in grads:
            updates, state = tx.update(g, state, p)
            p = optax.apply_updates(p, updates)
        expected = 1.0 - 0.1 * np.mean([1, 2, 3, 4])
        np.testing.assert_allclose(np.asarray(p["w"]), expected, rtol=1e-6)

    def test_graph_mode_api_raises(self):
        sync = SyncReplicasOptimizer(optax.sgd(0.1), 2)
        with pytest.raises(NotImplementedError):
            sync.apply_gradients([])


class TestDeviceSetterAndCrossDeviceOps:
    def test_replica_device_setter_noop(self):
        fn = replica_device_setter(ps_tasks=3)
        assert fn() == ""

    def test_nccl_allreduce_reduces(self):
        ops = NcclAllReduce(num_packs=2)
        out = ops.reduce("MEAN", jnp.arange(4.0))
        assert float(out) == pytest.approx(1.5)
        assert "ici" in ops.algorithm


class TestMonitoredTrainingSession:
    """The VERBATIM TF1 hot loop runs: with MTS(...) as sess:
    while not sess.should_stop(): sess.run(train_op)."""

    @staticmethod
    def _pieces(lr=0.1):
        import itertools

        from distributed_tensorflow_tpu.training import (
            FP32,
            TrainState,
            make_train_step,
        )

        def loss_fn(params, batch, rng):
            pred = batch["x"] @ params["w"]
            loss = jnp.mean((pred - batch["y"]) ** 2)
            return loss, {"loss": loss}

        params = {"w": jnp.zeros((4, 1))}
        state = TrainState.create(
            apply_fn=lambda p, x: x @ p["w"], params=params, tx=optax.sgd(lr)
        )
        rng = np.random.RandomState(0)
        x = rng.randn(64, 4).astype(np.float32)
        batch = {"x": x, "y": x @ np.ones((4, 1), np.float32)}
        train_op = make_train_step(loss_fn, precision=FP32)
        return state, train_op, itertools.repeat(batch)

    def test_verbatim_loop_stops_and_checkpoints(self, tmp_path):
        from distributed_tensorflow_tpu.compat import (
            MonitoredTrainingSession,
            StopAtStepHook,
        )

        state, train_op, data = self._pieces()
        ckpt = str(tmp_path / "ckpt")
        with MonitoredTrainingSession(
            is_chief=True,
            checkpoint_dir=ckpt,
            hooks=[StopAtStepHook(num_steps=5)],
            save_checkpoint_steps=5,
            state=state,
            data_iter=data,
            metrics_every=1,
        ) as sess:
            n = 0
            while not sess.should_stop():
                sess.run(train_op)
                n += 1
        assert n == 5
        assert int(jax.device_get(sess.state.step)) == 5
        # run() after stop is the TF1 error contract
        with pytest.raises(RuntimeError):
            sess.run(train_op)

    def test_session_resumes_from_checkpoint(self, tmp_path):
        from distributed_tensorflow_tpu.compat import (
            MonitoredTrainingSession,
            StopAtStepHook,
        )

        ckpt = str(tmp_path / "ckpt")
        state, train_op, data = self._pieces()
        with MonitoredTrainingSession(
            checkpoint_dir=ckpt, hooks=[StopAtStepHook(num_steps=5)],
            state=state, data_iter=data,
        ) as sess:
            while not sess.should_stop():
                sess.run(train_op)
        w_after_5 = np.asarray(jax.device_get(sess.state.params["w"]))

        # Fresh state; the session restores step 5 on __enter__ (the TF1
        # "session restores latest checkpoint" contract) and StopAtStepHook
        # (relative num_steps) runs exactly 3 more.
        state2, train_op2, data2 = self._pieces()
        with MonitoredTrainingSession(
            checkpoint_dir=ckpt, hooks=[StopAtStepHook(num_steps=3)],
            state=state2, data_iter=data2,
        ) as sess2:
            n = 0
            while not sess2.should_stop():
                sess2.run(train_op2)
                n += 1
        assert n == 3
        assert int(jax.device_get(sess2.state.step)) == 8
        # the restored weights were the trained ones, not the fresh zeros
        w_restored_path = np.asarray(jax.device_get(sess2.state.params["w"]))
        assert not np.allclose(w_restored_path, 0.0)
        assert np.linalg.norm(w_restored_path - w_after_5) > 0  # kept training

    def test_stop_at_step_requires_exactly_one_bound(self):
        from distributed_tensorflow_tpu.compat import StopAtStepHook

        with pytest.raises(ValueError):
            StopAtStepHook()
        with pytest.raises(ValueError):
            StopAtStepHook(num_steps=2, last_step=5)

    def test_session_requires_state(self):
        from distributed_tensorflow_tpu.compat import MonitoredTrainingSession

        with pytest.raises(ValueError):
            MonitoredTrainingSession()

    def test_tf1_fetch_list_idiom(self):
        """`_, step = sess.run([train_op, global_step])` ports directly."""
        from distributed_tensorflow_tpu.compat import (
            MonitoredTrainingSession,
            StopAtStepHook,
        )

        state, train_op, data = self._pieces()
        global_step = lambda s: s.step  # the TF1 global_step tensor role
        with MonitoredTrainingSession(
            hooks=[StopAtStepHook(num_steps=3)],
            state=state, data_iter=data, metrics_every=1,
        ) as sess:
            steps = []
            while not sess.should_stop():
                _, step = sess.run([train_op, global_step])
                steps.append(int(step))
        assert steps == [1, 2, 3]

    def test_feed_dict_positional_rejected(self):
        from distributed_tensorflow_tpu.compat import MonitoredTrainingSession

        state, train_op, data = self._pieces()
        with MonitoredTrainingSession(state=state, data_iter=data) as sess:
            with pytest.raises(TypeError, match="feed_dict"):
                sess.run(train_op, {"placeholder": 1})

    def test_exhausted_iterator_yields_no_fabricated_fetches(self):
        import itertools

        from distributed_tensorflow_tpu.compat import MonitoredTrainingSession

        state, train_op, _ = self._pieces()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        batch = {"x": x, "y": x @ np.ones((4, 1), np.float32)}
        finite = iter([batch, batch])  # exactly 2 batches
        global_step = lambda s: s.step
        with MonitoredTrainingSession(state=state, data_iter=finite,
                                      metrics_every=1) as sess:
            results = []
            while not sess.should_stop():
                results.append(sess.run([train_op, global_step]))
        # 2 real steps + the exhaustion call returning Nones
        assert len(results) == 3
        assert [int(r[1]) for r in results[:2]] == [1, 2]
        assert results[2] == [None, None]

    def test_non_callable_fetch_rejected(self):
        from distributed_tensorflow_tpu.compat import MonitoredTrainingSession

        state, train_op, data = self._pieces()
        with MonitoredTrainingSession(state=state, data_iter=data) as sess:
            with pytest.raises(TypeError, match="not callable"):
                sess.run([train_op, "global_step:0"])
