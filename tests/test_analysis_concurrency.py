"""dttlint v2 whole-program concurrency rules: each seeded fixture in
``tests/analysis_fixtures/`` is detected at its exact ``path:line``,
each clean twin stays silent, the real tree is clean end to end, and
deleting the engine's ``_launch_lock`` in a scratch copy makes
``collective-launch`` fire (machine-checking the PR 7 invariant)."""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from distributed_tensorflow_tpu.analysis import (
    default_rules,
    load_baseline,
    load_modules,
    run_rules,
    split_findings,
)
from distributed_tensorflow_tpu.analysis.__main__ import default_targets
from distributed_tensorflow_tpu.analysis.concurrency import (
    CollectiveLaunchRule,
    CrossThreadRaceRule,
    LockOrderRule,
    _FACTS_CACHE,
)
from distributed_tensorflow_tpu.analysis.core import collect_files
from distributed_tensorflow_tpu.analysis.sarif import sarif_dict

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURES = REPO_ROOT / "tests" / "analysis_fixtures"


def seeded_lines(path: Path, rule_id: str | None = None):
    """Lines carrying a ``# SEED`` marker — the exact expected findings.

    Fixtures shared across rule families tag lines ``# SEED: <rule-id>``;
    when ``rule_id`` is given and such tags exist, only those lines are
    claimed (older single-rule fixtures fall back to any ``# SEED``)."""
    lines = path.read_text().splitlines()
    if rule_id is not None:
        tagged = [i for i, line in enumerate(lines, 1)
                  if f"# SEED: {rule_id}" in line]
        if tagged:
            return tagged
    return [i for i, line in enumerate(lines, 1) if "# SEED" in line]


def run_rule_on(rule, path: Path, root: Path = REPO_ROOT):
    _FACTS_CACHE.clear()  # facts are keyed per module list; stay hermetic
    modules, errors = load_modules([path], root)
    assert not errors, errors
    return rule.run(modules)


class TestSeededFixtures:
    """Each bad fixture fires at exactly its SEED-marked lines; each
    clean twin produces zero findings from the same rule."""

    CASES = [
        ("lockorder", LockOrderRule, "lock-order"),
        ("blocking", LockOrderRule, "lock-order"),
        ("race", CrossThreadRaceRule, "cross-thread-race"),
        ("gateway", CrossThreadRaceRule, "cross-thread-race"),
        ("tiering", CrossThreadRaceRule, "cross-thread-race"),
        ("lifecycle", CrossThreadRaceRule, "cross-thread-race"),
        ("launch", CollectiveLaunchRule, "collective-launch"),
        ("megastep", CollectiveLaunchRule, "collective-launch"),
        ("spec", CollectiveLaunchRule, "collective-launch"),
        ("asyncring", CollectiveLaunchRule, "collective-launch"),
    ]

    @pytest.mark.parametrize("stem,rule_cls,rule_id",
                             CASES, ids=[c[0] for c in CASES])
    def test_bad_fixture_detected_at_exact_lines(self, stem, rule_cls,
                                                 rule_id):
        path = FIXTURES / f"{stem}_bad.py"
        expected = seeded_lines(path, rule_id)
        assert expected, f"{path} lost its SEED markers"
        findings = run_rule_on(rule_cls(), path)
        assert sorted(f.line for f in findings) == sorted(expected), [
            f"{f.path}:{f.line} {f.message}" for f in findings]
        relpath = path.relative_to(REPO_ROOT).as_posix()
        for f in findings:
            assert f.rule == rule_id
            assert f.path == relpath

    @pytest.mark.parametrize("stem,rule_cls,rule_id",
                             CASES, ids=[c[0] for c in CASES])
    def test_clean_twin_is_silent(self, stem, rule_cls, rule_id):
        findings = run_rule_on(rule_cls(), FIXTURES / f"{stem}_clean.py")
        assert findings == [], [
            f"{f.path}:{f.line} {f.message}" for f in findings]

    def test_blocking_fixture_is_warning_tier(self):
        findings = run_rule_on(LockOrderRule(), FIXTURES / "blocking_bad.py")
        assert all(f.severity == "warning" for f in findings)

    def test_lockorder_fixture_names_both_groups(self):
        findings = run_rule_on(LockOrderRule(), FIXTURES / "lockorder_bad.py")
        msgs = " ".join(f.message for f in findings)
        assert "Alpha._lock" in msgs and "Beta._lock" in msgs


class TestRealTreeClean:
    """The tree-wide gate, in-process: full default targets, full rule
    set, every finding either absent or justified in the baseline."""

    def test_full_tree_zero_unjustified_findings(self):
        _FACTS_CACHE.clear()
        files = collect_files(default_targets(REPO_ROOT), REPO_ROOT)
        modules, errors = load_modules(files, REPO_ROOT)
        assert not errors, errors
        findings = run_rules(modules, default_rules())
        entries = load_baseline(
            REPO_ROOT / "distributed_tensorflow_tpu" / "analysis"
            / "baseline.json")
        new, baselined, stale = split_findings(findings, entries)
        assert new == [], [
            f"{f.rule} {f.path}:{f.line} {f.message}" for f in new]
        assert stale == [], stale


class TestLaunchLockInvariant:
    """Deleting PR 7's ``_launch_lock`` acquisitions in a scratch copy
    of the tree makes ``collective-launch`` fire on engine.py — the
    rule actually guards the invariant, not just the fixture."""

    def test_removing_launch_lock_trips_rule(self, tmp_path):
        scratch = tmp_path / "scratch"
        shutil.copytree(
            REPO_ROOT / "distributed_tensorflow_tpu",
            scratch / "distributed_tensorflow_tpu",
            ignore=shutil.ignore_patterns("__pycache__"))
        engine = scratch / "distributed_tensorflow_tpu" / "serve" / "engine.py"
        src = engine.read_text()
        assert "with _launch_lock:" in src
        engine.write_text(src.replace("with _launch_lock:", "if True:"))

        _FACTS_CACHE.clear()
        files = collect_files([scratch / "distributed_tensorflow_tpu"],
                              scratch)
        modules, errors = load_modules(files, scratch)
        assert not errors, errors
        findings = CollectiveLaunchRule().run(modules)
        engine_hits = [f for f in findings
                       if f.path == "distributed_tensorflow_tpu/serve/engine.py"]
        assert engine_hits, "unlocked launches in engine.py went undetected"
        _FACTS_CACHE.clear()

    def test_real_tree_engine_is_currently_clean(self):
        _FACTS_CACHE.clear()
        files = collect_files([REPO_ROOT / "distributed_tensorflow_tpu"],
                              REPO_ROOT)
        modules, errors = load_modules(files, REPO_ROOT)
        assert not errors, errors
        assert CollectiveLaunchRule().run(modules) == []


class TestCli:
    """The new runner surface: --changed-only, --prune, stale-as-error,
    and SARIF output."""

    def _run(self, *argv, stdin=None, cwd=REPO_ROOT):
        return subprocess.run(
            [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
             *argv],
            input=stdin, capture_output=True, text=True, cwd=cwd,
            timeout=300)

    def test_changed_only_reads_stdin(self):
        listed = ("distributed_tensorflow_tpu/analysis/sarif.py\n"
                  "docs/not-python.md\n"
                  "distributed_tensorflow_tpu/analysis/core.py\n")
        proc = self._run("--changed-only", stdin=listed)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "2 files" in proc.stdout

    def test_changed_only_empty_input_is_clean_noop(self):
        proc = self._run("--changed-only", stdin="")
        assert proc.returncode == 0
        assert "nothing to analyze" in proc.stdout

    def test_changed_only_rejects_explicit_paths(self):
        proc = self._run("--changed-only", "train.py", stdin="")
        assert proc.returncode == 2

    def test_stale_entry_errors_on_full_run_and_prune_drops_it(
            self, tmp_path):
        real = json.loads(
            (REPO_ROOT / "distributed_tensorflow_tpu" / "analysis"
             / "baseline.json").read_text())
        real["entries"].append({
            "rule": "lock-discipline",
            "path": "distributed_tensorflow_tpu/serve/engine.py",
            "code": "self.never_matches_anything = 1",
            "justification": "stale on purpose",
        })
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(real))

        proc = self._run("--baseline", str(bl))
        assert proc.returncode == 1, proc.stdout
        assert "stale baseline entry" in proc.stdout
        assert "--prune" in proc.stdout

        proc = self._run("--baseline", str(bl), "--prune")
        assert proc.returncode == 0, proc.stdout
        assert "pruned 1" in proc.stdout
        kept = json.loads(bl.read_text())["entries"]
        assert all(e["code"] != "self.never_matches_anything = 1"
                   for e in kept)

        proc = self._run("--baseline", str(bl))
        assert proc.returncode == 0, proc.stdout

    def test_prune_refuses_partial_runs(self):
        proc = self._run("--prune", "--rules", "lock-discipline")
        assert proc.returncode == 2
        assert "full default run" in proc.stderr

    def test_sarif_format_on_seeded_fixture(self):
        proc = self._run("--format=sarif", "--no-baseline",
                         str(FIXTURES / "race_bad.py"))
        assert proc.returncode == 1  # seeded finding present
        log = json.loads(proc.stdout)
        assert log["version"] == "2.1.0"
        run = log["runs"][0]
        assert run["tool"]["driver"]["name"] == "dttlint"
        results = run["results"]
        race = [r for r in results if r["ruleId"] == "cross-thread-race"]
        assert race, results
        loc = race[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == \
            "tests/analysis_fixtures/race_bad.py"
        assert loc["region"]["startLine"] in seeded_lines(
            FIXTURES / "race_bad.py")
        rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
        assert race[0]["ruleIndex"] == rule_ids.index("cross-thread-race")

    def test_sarif_out_writes_file(self, tmp_path):
        out = tmp_path / "report.sarif"
        proc = self._run("--sarif-out", str(out), "--no-baseline",
                         str(FIXTURES / "launch_clean.py"))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        log = json.loads(out.read_text())
        assert log["runs"][0]["results"] == []


class TestSarifUnit:
    def test_severity_maps_to_level(self):
        from distributed_tensorflow_tpu.analysis.core import Finding
        fs = [Finding(rule="lock-order", path="a.py", line=3,
                      message="m", severity="warning"),
              Finding(rule="lock-order", path="a.py", line=4,
                      message="n")]
        log = sarif_dict(fs, default_rules())
        levels = [r["level"] for r in log["runs"][0]["results"]]
        assert levels == ["warning", "error"]
