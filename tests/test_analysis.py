"""dttlint analyzer tests: each rule family catches its seeded fixture
at the right rule id and line, suppressions and the baseline round-trip
work, and — the tier-1 gate — the repo itself is clean."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from distributed_tensorflow_tpu.analysis import (
    collect_files,
    default_rules,
    load_baseline,
    load_modules,
    render_baseline,
    run_rules,
    split_findings,
)
from distributed_tensorflow_tpu.analysis.baseline import BaselineError
from distributed_tensorflow_tpu.analysis.core import Finding, Module

REPO_ROOT = Path(__file__).resolve().parent.parent


def lint_source(tmp_path, source, filename="fixture.py", repo_root=None):
    """Write a fixture, run the full default rule set, return findings."""
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    root = repo_root or tmp_path
    modules, errors = load_modules([path], root)
    assert not errors, errors
    return run_rules(modules, default_rules())


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


class TestJitPurity:
    def test_decorated_function_impurities(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import time
            import logging
            import jax

            logger = logging.getLogger(__name__)

            @jax.jit
            def step(x):
                t0 = time.time()
                logger.info("tick")
                print(x)
                return x + t0
            """)
        purity = by_rule(findings, "jit-purity")
        assert [f.line for f in purity] == [9, 10, 11]
        assert "time.time" in purity[0].message
        assert "logger.info" in purity[1].message
        assert "print" in purity[2].message

    def test_call_graph_walk_reaches_helper(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import random
            import jax

            def helper(x):
                return x * random.random()

            def outer(x):
                return helper(x)

            fn = jax.jit(outer)
            """)
        purity = by_rule(findings, "jit-purity")
        assert len(purity) == 1
        assert purity[0].line == 5
        assert "random.random" in purity[0].message

    def test_jax_random_is_pure(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import jax

            @jax.jit
            def step(key, x):
                noise = jax.random.normal(key, x.shape)
                return x + noise
            """)
        assert by_rule(findings, "jit-purity") == []

    def test_numpy_random_alias_resolved(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import numpy as np
            import jax

            @jax.jit
            def step(x):
                return x + np.random.rand()
            """)
        purity = by_rule(findings, "jit-purity")
        assert len(purity) == 1 and purity[0].line == 6
        assert "numpy.random" in purity[0].message

    def test_obs_instrument_handle_flagged(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import jax

            class Engine:
                def _step(self, x):
                    self._obs["steps"].inc()
                    return x

                def compile(self):
                    return jax.jit(self._step)
            """)
        purity = by_rule(findings, "jit-purity")
        assert len(purity) == 1 and purity[0].line == 5


class TestRecompileHazard:
    def test_unhashable_static_arg(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import jax

            def f(x, opts=[]):
                return x

            g = jax.jit(f, static_argnums=(1,))
            """)
        hazards = by_rule(findings, "recompile-hazard")
        assert len(hazards) == 1 and hazards[0].line == 6
        assert "opts" in hazards[0].message

    def test_nonfrozen_dataclass_cache_key(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import dataclasses
            import jax

            @dataclasses.dataclass
            class Cfg:
                n: int = 1

            class Engine:
                def __init__(self):
                    self._fns = {}

                def get(self, cfg: Cfg, temp):
                    key = (float(temp), cfg)
                    self._fns[key] = jax.jit(lambda x: x * temp)
                    return self._fns[key]
            """)
        hazards = by_rule(findings, "recompile-hazard")
        assert len(hazards) == 1 and hazards[0].line == 14
        assert "Cfg" in hazards[0].message

    def test_frozen_dataclass_key_is_fine(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import dataclasses
            import jax

            @dataclasses.dataclass(frozen=True)
            class Cfg:
                n: int = 1

            class Engine:
                def __init__(self):
                    self._fns = {}

                def get(self, cfg: Cfg, temp):
                    key = (float(temp), cfg)
                    self._fns[key] = jax.jit(lambda x: x * temp)
                    return self._fns[key]
            """)
        assert by_rule(findings, "recompile-hazard") == []

    def test_sampling_bad_fixture_fires_at_seeded_lines(self):
        """The per-request-scalar-in-key antipattern the vectorized
        sampling path removed: a non-frozen config in the program-cache
        key (or baked into a jitted partial) fires at every SEED line."""
        path = REPO_ROOT / "tests" / "analysis_fixtures" / "sampling_bad.py"
        expected = [i for i, line in
                    enumerate(path.read_text().splitlines(), 1)
                    if "# SEED: recompile-hazard" in line]
        assert expected, f"{path} has no SEED markers"
        modules, errors = load_modules([path], REPO_ROOT)
        assert not errors, errors
        hazards = by_rule(run_rules(modules, default_rules()),
                          "recompile-hazard")
        assert sorted(f.line for f in hazards) == expected

    def test_sampling_clean_twin_is_silent(self):
        """Frozen params + static family keys + runtime vectors — the
        serve.sampling pattern — produce zero findings from ANY rule."""
        path = REPO_ROOT / "tests" / "analysis_fixtures" / "sampling_clean.py"
        modules, errors = load_modules([path], REPO_ROOT)
        assert not errors, errors
        assert run_rules(modules, default_rules()) == []

    def test_mutable_closure_capture(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import jax

            def make(scale0):
                state = [scale0]

                def inner(x):
                    return x * state[0]

                return jax.jit(inner)
            """)
        hazards = by_rule(findings, "recompile-hazard")
        assert len(hazards) == 1 and hazards[0].line == 9
        assert "state" in hazards[0].message


class TestLockDiscipline:
    def test_unlocked_write_flagged(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import threading

            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0

                def inc(self):
                    with self._lock:
                        self._count += 1

                def reset(self):
                    self._count = 0
            """)
        locks = by_rule(findings, "lock-discipline")
        assert len(locks) == 1
        assert locks[0].line == 13
        assert "_count" in locks[0].message
        assert locks[0].symbol == "Stats.reset"

    def test_condition_aliases_wrapped_lock(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)
                        self._cv.notify()

                def get(self):
                    with self._cv:
                        return self._items.pop()
            """)
        assert by_rule(findings, "lock-discipline") == []

    def test_init_and_init_reachable_methods_exempt(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []
                    self._replay()

                def _replay(self):
                    self._items.append(1)

                def add(self, x):
                    with self._lock:
                        self._items.append(x)
            """)
        assert by_rule(findings, "lock-discipline") == []

    def test_locked_suffix_means_caller_holds(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._bump_locked()

                def _bump_locked(self):
                    self._n += 1
            """)
        assert by_rule(findings, "lock-discipline") == []


class TestLayering:
    def test_obs_core_must_not_import_jax(self, tmp_path):
        pkg = tmp_path / "distributed_tensorflow_tpu" / "obs"
        findings = lint_source(
            tmp_path, """\
            import jax

            def snapshot():
                return jax.device_count()
            """,
            filename="distributed_tensorflow_tpu/obs/metrics.py")
        layer = by_rule(findings, "layering")
        assert len(layer) == 1 and layer[0].line == 1
        assert "jax" in layer[0].message
        assert pkg.joinpath("metrics.py").exists()

    def test_training_must_not_import_serve_even_lazily(self, tmp_path):
        findings = lint_source(
            tmp_path, """\
            def hook():
                from distributed_tensorflow_tpu.serve import engine
                return engine
            """,
            filename="distributed_tensorflow_tpu/training/loop.py")
        layer = by_rule(findings, "layering")
        assert len(layer) == 1 and layer[0].line == 2
        assert "even lazily" in layer[0].message

    def test_toplevel_cycle_detected(self, tmp_path):
        a = tmp_path / "distributed_tensorflow_tpu" / "x.py"
        b = tmp_path / "distributed_tensorflow_tpu" / "y.py"
        a.parent.mkdir(parents=True, exist_ok=True)
        a.write_text("from distributed_tensorflow_tpu.y import g\n"
                     "def f():\n    return g()\n")
        b.write_text("from distributed_tensorflow_tpu.x import f\n"
                     "def g():\n    return f()\n")
        modules, errors = load_modules([a, b], tmp_path)
        assert not errors
        findings = run_rules(modules, default_rules())
        cycles = [f for f in by_rule(findings, "layering")
                  if "cycle" in f.message]
        assert len(cycles) == 1

    def test_lazy_import_breaks_cycle(self, tmp_path):
        a = tmp_path / "distributed_tensorflow_tpu" / "x.py"
        b = tmp_path / "distributed_tensorflow_tpu" / "y.py"
        a.parent.mkdir(parents=True, exist_ok=True)
        a.write_text("from distributed_tensorflow_tpu.y import g\n"
                     "def f():\n    return g()\n")
        b.write_text("def g():\n"
                     "    from distributed_tensorflow_tpu.x import f\n"
                     "    return f()\n")
        modules, errors = load_modules([a, b], tmp_path)
        assert not errors
        findings = run_rules(modules, default_rules())
        cycles = [f for f in by_rule(findings, "layering")
                  if "cycle" in f.message]
        assert cycles == []


class TestHygiene:
    def test_unused_import_and_mutable_default(self, tmp_path):
        findings = lint_source(tmp_path, """\
            import itertools
            import json


            def f(xs=[]):
                return json.dumps(xs)
            """)
        unused = by_rule(findings, "unused-import")
        assert len(unused) == 1 and unused[0].line == 1
        assert "itertools" in unused[0].message
        mutable = by_rule(findings, "mutable-default")
        assert len(mutable) == 1 and mutable[0].line == 5


class TestSuppressions:
    SOURCE = """\
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def inc(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                return self._n{trailing}
        """

    def test_trailing_comment_suppresses(self, tmp_path):
        findings = lint_source(tmp_path, self.SOURCE.format(
            trailing="  # dttlint: disable=lock-discipline"))
        assert by_rule(findings, "lock-discipline") == []

    def test_other_rule_not_suppressed(self, tmp_path):
        findings = lint_source(tmp_path, self.SOURCE.format(
            trailing="  # dttlint: disable=jit-purity"))
        assert len(by_rule(findings, "lock-discipline")) == 1

    def test_preceding_line_comment_suppresses(self, tmp_path):
        source = textwrap.dedent(self.SOURCE.format(trailing="")).replace(
            "        return self._n",
            "        # dttlint: disable=lock-discipline\n"
            "        return self._n")
        findings = lint_source(tmp_path, source)
        assert by_rule(findings, "lock-discipline") == []

    def test_disable_file(self, tmp_path):
        source = ("# dttlint: disable-file=lock-discipline\n"
                  + textwrap.dedent(self.SOURCE.format(trailing="")))
        findings = lint_source(tmp_path, source)
        assert by_rule(findings, "lock-discipline") == []


class TestBaseline:
    def test_round_trip(self, tmp_path):
        findings = [
            Finding(rule="lock-discipline", path="a/b.py", line=12,
                    message="unlocked read", code="return self._n"),
            Finding(rule="jit-purity", path="c.py", line=3,
                    message="print", code="print(x)"),
        ]
        path = tmp_path / "baseline.json"
        path.write_text(render_baseline(findings, justification="benign"))
        entries = load_baseline(path)
        assert len(entries) == 2
        new, baselined, stale = split_findings(findings, entries)
        assert new == [] and len(baselined) == 2 and stale == []

    def test_line_drift_still_matches(self, tmp_path):
        finding = Finding(rule="lock-discipline", path="a.py", line=40,
                          message="unlocked read", code="return self._n")
        path = tmp_path / "baseline.json"
        path.write_text(render_baseline([finding], justification="benign"))
        drifted = Finding(rule="lock-discipline", path="a.py", line=97,
                          message="unlocked read", code="return self._n")
        new, baselined, stale = split_findings(
            [drifted], load_baseline(path))
        assert new == [] and len(baselined) == 1

    def test_missing_justification_rejected(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"entries": [{
            "rule": "lock-discipline", "path": "a.py",
            "code": "return self._n", "justification": "  "}]}))
        with pytest.raises(BaselineError):
            load_baseline(path)

    def test_stale_entry_reported(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"entries": [{
            "rule": "lock-discipline", "path": "gone.py",
            "code": "return self._n", "justification": "was removed"}]}))
        new, baselined, stale = split_findings([], load_baseline(path))
        assert new == [] and baselined == [] and len(stale) == 1

    def test_repo_baseline_is_wellformed(self):
        entries = load_baseline(
            REPO_ROOT / "distributed_tensorflow_tpu" / "analysis"
            / "baseline.json")
        for e in entries:
            assert e["justification"].strip()


class TestRepoGate:
    """The self-enforcing tier-1 gate: the tree must be dttlint-clean."""

    def test_repo_has_zero_nonbaselined_findings(self):
        proc = subprocess.run(
            [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
             "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=300)
        assert proc.returncode == 0, (
            "dttlint found non-baselined findings:\n" + proc.stdout[-8000:]
            + proc.stderr[-2000:])
        report = json.loads(proc.stdout)
        assert report["findings"] == []
        assert report["files"] > 50  # the sweep really covered the tree

    def test_runner_flags_seeded_violation(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("import threading\n\n"
                       "class S:\n"
                       "    def __init__(self):\n"
                       "        self._lock = threading.Lock()\n"
                       "        self._n = 0\n\n"
                       "    def inc(self):\n"
                       "        with self._lock:\n"
                       "            self._n += 1\n\n"
                       "    def peek(self):\n"
                       "        return self._n\n")
        proc = subprocess.run(
            [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
             "--no-baseline", str(bad)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 1
        assert "lock-discipline" in proc.stdout

    def test_analysis_package_imports_without_jax(self):
        # The analyzer must stay usable in a jax-free interpreter: no
        # analysis module may import jax at module scope.
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.modules['jax'] = None; "
             "import distributed_tensorflow_tpu.analysis; "
             "import distributed_tensorflow_tpu.analysis.__main__; "
             "print('ok')"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ok" in proc.stdout


class TestCollectFiles:
    def test_tests_dir_excluded_from_directory_sweep(self, tmp_path):
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "a.py").write_text("x = 1\n")
        (tmp_path / "tests").mkdir()
        (tmp_path / "tests" / "test_a.py").write_text("x = 1\n")
        files = collect_files([tmp_path], tmp_path)
        names = {f.name for f in files}
        assert "a.py" in names and "test_a.py" not in names

    def test_module_names_derived_from_repo_root(self, tmp_path):
        p = tmp_path / "distributed_tensorflow_tpu" / "obs" / "metrics.py"
        p.parent.mkdir(parents=True)
        p.write_text("x = 1\n")
        modules, _ = load_modules([p], tmp_path)
        assert modules[0].name == "distributed_tensorflow_tpu.obs.metrics"
