"""Concurrency stress: ≥8 threads hammering the two shared hot objects —
``obs.metrics.Registry`` and ``serve.DynamicBatcher`` — asserting no lost
updates, no exceptions, and clean shutdown.  These are the dynamic
counterpart of dttlint's static ``lock-discipline`` rule: the rule proves
accesses sit under the lock, this proves the lock actually serializes
them."""

import threading
import time
from concurrent.futures import Future


from distributed_tensorflow_tpu.obs.metrics import Registry
from distributed_tensorflow_tpu.serve.batcher import (
    DynamicBatcher,
    ServeOverloadedError,
)

N_THREADS = 8
OPS_PER_THREAD = 500


def _run_threads(worker, n=N_THREADS):
    """Start n workers against a barrier, join them, raise any errors."""
    barrier = threading.Barrier(n)
    errors = []

    def wrapped(i):
        try:
            barrier.wait(timeout=10)
            worker(i)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=wrapped, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged"
    assert errors == [], errors


class TestRegistryStress:
    def test_counter_no_lost_updates(self):
        registry = Registry()
        counter = registry.counter("stress_total", "stress counter")

        def worker(i):
            for _ in range(OPS_PER_THREAD):
                counter.inc()

        _run_threads(worker)
        assert counter.value == N_THREADS * OPS_PER_THREAD

    def test_labeled_families_and_histograms_race_free(self):
        registry = Registry()

        def worker(i):
            # Every thread races get-or-create on the SAME names: the
            # registry must hand back one family, one child per label.
            for k in range(OPS_PER_THREAD):
                registry.counter(
                    "stress_labeled", "labeled", labelnames=("t",)
                ).labels(t=str(i % 4)).inc()
                registry.histogram(
                    "stress_hist", "hist", buckets=(0.1, 1.0, 10.0)
                ).observe(float(k % 7))

        _run_threads(worker)
        total = sum(
            child.value
            for _labels, child in registry.counter(
                "stress_labeled", "labeled", labelnames=("t",)).samples())
        assert total == N_THREADS * OPS_PER_THREAD
        hist = registry.histogram("stress_hist", "hist",
                                  buckets=(0.1, 1.0, 10.0))
        assert hist.count == N_THREADS * OPS_PER_THREAD

    def test_stats_providers_register_during_reads(self):
        registry = Registry()

        def worker(i):
            for k in range(100):
                ns = registry.register_stats(
                    f"stress/{i}/{k}", lambda: {"x": 1.0})
                assert ns

        _run_threads(worker)


class TestBatcherStress:
    def test_submit_from_8_threads_no_lost_requests(self):
        processed = []
        processed_lock = threading.Lock()

        def run_batch(payloads):
            with processed_lock:
                processed.extend(payloads)
            return [p * 2 for p in payloads]

        batcher = DynamicBatcher(
            run_batch, max_batch_size=16, batch_timeout_ms=1.0,
            max_queue_size=10_000, name="stress")
        results = []
        results_lock = threading.Lock()

        def worker(i):
            futures = []
            for k in range(OPS_PER_THREAD):
                futures.append((i * OPS_PER_THREAD + k,
                                batcher.submit(i * OPS_PER_THREAD + k)))
            for payload, fut in futures:
                assert fut.result(timeout=30) == payload * 2
            with results_lock:
                results.append(len(futures))

        try:
            _run_threads(worker)
        finally:
            batcher.close()
        assert sum(results) == N_THREADS * OPS_PER_THREAD
        assert sorted(processed) == list(range(N_THREADS * OPS_PER_THREAD))
        stats = batcher.stats()
        assert stats["submitted"] == N_THREADS * OPS_PER_THREAD
        assert stats["completed"] == N_THREADS * OPS_PER_THREAD
        assert stats["failed"] == 0

    def test_shutdown_races_submit_cleanly(self):
        # Half the threads submit while the main thread closes the
        # batcher mid-flight: every future must resolve (result or
        # RuntimeError/overload rejection) — nothing may hang or leak an
        # unexpected exception type.
        def run_batch(payloads):
            time.sleep(0.001)
            return payloads

        batcher = DynamicBatcher(
            run_batch, max_batch_size=4, batch_timeout_ms=1.0,
            max_queue_size=256, name="stress-shutdown")
        futures = []
        futures_lock = threading.Lock()
        stop = threading.Event()

        def worker(i):
            while not stop.is_set():
                try:
                    fut = batcher.submit(i)
                except (ServeOverloadedError, RuntimeError):
                    continue  # overload or already-closed are both clean
                with futures_lock:
                    futures.append(fut)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(N_THREADS)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        batcher.close(timeout=10.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "submitter wedged after close()"
        resolved = 0
        for fut in futures:
            assert isinstance(fut, Future)
            try:
                fut.result(timeout=10)
                resolved += 1
            except RuntimeError:
                resolved += 1  # drained-at-shutdown rejection is clean
        assert resolved == len(futures)

    def test_close_is_idempotent_under_contention(self):
        batcher = DynamicBatcher(lambda p: p, max_batch_size=2,
                                 batch_timeout_ms=1.0, name="stress-close")

        def worker(i):
            batcher.close(timeout=5.0)

        _run_threads(worker)
